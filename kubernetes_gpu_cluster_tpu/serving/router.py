"""Request router: one front door over N data-parallel engine replicas.

The reference exposed its replicas behind ``vllm-router-service`` and
operators port-forwarded to it (``old_README.md:1174-1176, 1472-1476``);
replicas were plain Deployment pods spread by anti-affinity
(``values-01-minimal-example2.yaml:10, 23-49``). This router is the native
equivalent: an aiohttp reverse proxy that

- tracks replica health (probed immediately at startup, then periodic GET
  /health; unhealthy replicas leave the rotation and return on recovery —
  the k8s-native restart/rollout story of SURVEY §5.3 at the traffic layer),
- balances by one of two policies (``--routing-policy``):

  * ``least-inflight`` (default): least-outstanding-requests — better than
    round-robin under continuous batching (a replica stuck on long
    generations accumulates in-flight count and sheds new work), but it
    scatters a session's requests across replicas, destroying the engine-
    side prefix-cache hits that collapse warm TTFT;
  * ``prefix-affinity``: bounded-load consistent hashing (CHWBL) keyed on
    the request's prompt prefix — the first ``affinity_prefix_len`` tokens'
    bytes, or an explicit ``session_id``/``user`` field when the body
    carries one. The key hashes onto a replica ring with virtual nodes;
    the ring owner serves it unless admitting one more request would push
    it past ``ceil(balance_factor * (total_inflight + 1) / n_replicas)``,
    in which case the walk continues to the next under-bound replica — hot
    prefixes still spread, cold traffic never evicts a warm replica's
    cache. Unhealthy/benched/excluded replicas are skipped on the same
    walk, so membership churn remaps only the dead replica's keys
    (~K/N of K keys, the consistent-hashing contract) and every key's
    assignment is deterministic across router restarts (hashes come from
    :mod:`hashlib`, never the process-salted builtin ``hash``). When no
    affinity key can be derived (GET /v1/models, unparseable body) or
    every ring candidate is over-bound, the pick degrades to
    least-inflight over the same candidates — never a 5xx,

- streams responses through unbuffered (SSE passthrough),
- hardens every upstream call: per-attempt connect timeouts, a per-read
  stall timeout that circuit-breaks replicas whose in-flight streams hang,
  and bounded exponential-backoff retry of connect-phase failures (the only
  phase where nothing reached the upstream, so re-sending is safe),
- traces every request: the router mints ``x-kgct-request-id`` (honoring an
  inbound header), forwards it to the replica — whose api_server adopts it
  as the ENGINE request id, so the engine's lifecycle trace shares the id —
  and echoes it on every response, success or error. Its own span stream
  (pick with policy/owner attribution, connect retries, upstream TTFB,
  stream relay) lands in a request tracer mirrored into a black-box flight
  recorder; ``GET /debug/trace`` merges the router's spans with each
  healthy replica's ``/debug/trace`` (bounded per-replica fetches, same
  straggler discipline as the metrics scrape) into ONE Perfetto timeline
  with per-process tracks, and ``GET /debug/flightrecorder`` exposes the
  crash-capture ring.

Every pick path — first attempt, connect-phase retry-with-exclude, the
desperation rounds over benched replicas — flows through the single
``_pick`` seam (pinned by the KGCT011 lint rule), so both policies inherit
the circuit-breaking/retry machinery unchanged.

Chaos sites (resilience.faults): ``router_connect`` simulates a connect
failure on the picked replica, ``replica_hang`` a mid-stream read timeout,
``replica_down`` forces the health probe of replica index ``value`` to
fail (drain/death remap of ring-owned keys), ``replica_kill_midstream``
severs the upstream socket after N relayed chunks (mid-stream failover /
resume ladder).

Session survivability: on a migration-capable fleet (>1 replica) the
router names each SSE completion's drain-push target in
MIGRATE_URL_HEADER (the key's ring successor), parses the relay to keep
the replica-embedded token ledger (stripped before the client), and when
the upstream dies before ``[DONE]`` re-dispatches to ring successors via
``POST /internal/resume`` — parked-KV import where the dying replica
managed a push, token replay otherwise — splicing the resumed stream so
the client sees ONE uninterrupted response; bounded attempts end in a
clean truncated-stream error frame carrying the request id
(``kgct_failovers_total{outcome=}``, ``kgct_router_failover_seconds``).

In-cluster, replica discovery is the headless-Service DNS name; static URLs
work for local/dev. Deployment manifests are rendered by
kubernetes_gpu_cluster_tpu.deploy (router Deployment + kgct-router-service;
``prefix-affinity`` renders single-host models as StatefulSets so every
replica pod has a stable DNS name the ring can own).
"""

from __future__ import annotations

import asyncio
import contextlib
import bisect
import hashlib
import json
import math
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from ..observability.flightrecorder import FlightRecorder
from ..observability.prometheus import Histogram
from ..observability.trace import RequestTracer, merge_perfetto
from ..resilience.faults import get_injector as _get_injector
from ..resilience.faults import inject as _inject_fault
from ..utils import get_logger
# The engine's shed/drain responses use the same envelope (serving.errors):
# a router-level 503 is handled by the identical client code path.
from .errors import (MIGRATE_URL_HEADER, PREFILL_URL_HEADER,
                     PREFIX_SOURCE_HEADER, QOS_TIER_HEADER,
                     REQUEST_ID_HEADER, RESUME_MODE_HEADER,
                     valid_request_id)
from .errors import overloaded_error as _proxy_error
from .fleet_cache import PeerScoreboard

logger = get_logger("serving.router")

# Connect-PHASE failures: nothing reached the upstream, so failover/retry is
# provably safe. ConnectionTimeoutError (sock_connect expired — the
# blackholed-node case, no RST ever comes back) is distinct from the
# sock_read ServerTimeoutError and joins the refused/unreachable class;
# older aiohttp without the split falls back to connector errors only.
CONNECT_PHASE_ERRORS: tuple = (aiohttp.ClientConnectorError,
                               ConnectionRefusedError)
if hasattr(aiohttp, "ConnectionTimeoutError"):
    CONNECT_PHASE_ERRORS += (aiohttp.ConnectionTimeoutError,)

HOP_HEADERS = {"transfer-encoding", "content-length", "connection",
               "keep-alive", "host"}

# Virtual nodes per replica on the consistent-hash ring. 64 points keep the
# per-replica share of RAW key space within ~1.6x fair at small N (pinned by
# the balance property test) while the ring stays tiny (N*64 bisect points);
# the CHWBL load bound — not vnode count — is what bounds actual load skew.
RING_VNODES = 64

# Mid-stream failover: how many ring successors a broken SSE relay may be
# re-dispatched to (POST /internal/resume) before the client gets the
# truncated-stream error. Small on purpose — each attempt re-prefills in
# the worst (token-replay) case.
FAILOVER_ATTEMPTS = 2


class _SSERelay:
    """Incremental SSE frame parser for migration-capable stream relays.

    The replica embeds each frame's new token ids under ``kgct_token_ids``
    (opted in by the MIGRATE_URL_HEADER the router itself sets); this
    parser strips the field before the bytes reach the client and keeps
    the running token ledger — exactly what a mid-stream failover replays
    to a ring successor. Frames without the field pass through
    byte-identical; a partial frame at the moment of upstream death stays
    in the buffer and never reaches the client, so the ledger always
    matches the delivered text."""

    def __init__(self):
        self._buf = b""
        self.tokens: list[int] = []
        self.done = False          # saw the terminal [DONE] frame
        self.finished = False      # saw a finish_reason-stamped frame: the
                                   # completion is semantically complete
                                   # even if [DONE] never arrives
        self.frames = 0

    def reset_buffer(self) -> None:
        """Drop a dead upstream's partial frame before splicing a resumed
        stream in — stale bytes would corrupt the next upstream's framing.
        The token ledger survives: it covers only fully-relayed frames."""
        self._buf = b""

    def feed(self, chunk: bytes) -> bytes:
        self._buf += chunk
        out = []
        while b"\n\n" in self._buf:
            frame, self._buf = self._buf.split(b"\n\n", 1)
            out.append(self._render(frame))
        return b"".join(out)

    def _render(self, frame: bytes) -> bytes:
        data_lines = [l for l in frame.split(b"\n")
                      if l.startswith(b"data:")]
        payload = b"\n".join(l[5:].strip() for l in data_lines)
        if payload == b"[DONE]":
            self.done = True
            return frame + b"\n\n"
        try:
            obj = json.loads(payload)
        except ValueError:
            return frame + b"\n\n"
        self.frames += 1
        if isinstance(obj, dict):
            try:
                if obj["choices"][0].get("finish_reason"):
                    self.finished = True
            except (LookupError, AttributeError, TypeError):
                pass
        if isinstance(obj, dict) and "kgct_token_ids" in obj:
            toks = obj.pop("kgct_token_ids")
            if isinstance(toks, list):
                self.tokens.extend(int(t) for t in toks)
            return b"data: " + json.dumps(obj).encode() + b"\n\n"
        return frame + b"\n\n"


def _stable_hash(data: bytes) -> int:
    """Ring/key hash: process-stable and platform-stable. The builtin
    ``hash`` is salted per process (PYTHONHASHSEED), which would silently
    give every router restart a different ring — the exact nondeterminism
    the affinity contract forbids. blake2b is the fastest stdlib digest."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "big")


class HashRing:
    """Consistent-hash ring over replica URLs with virtual nodes.

    Membership is fixed at construction (the rendered replica set); health
    churn is handled by the CALLER skipping dead entries while walking from
    the owner — equivalent to removing them from the ring (each dead
    replica's keys land on their ring successors; everyone else's keys do
    not move), without rebuild races. Identical URL lists produce identical
    rings in any process (see :func:`_stable_hash`)."""

    def __init__(self, urls: list[str], vnodes: int = RING_VNODES):
        points: list[tuple[int, str]] = []
        for url in urls:
            for v in range(vnodes):
                points.append((_stable_hash(f"{url}#{v}".encode()), url))
        points.sort()
        self._points = [p for p, _ in points]
        self._urls = [u for _, u in points]

    def owner(self, key: bytes) -> str:
        """The ring owner of ``key`` (ignores health — the metrics notion
        of 'where this key lives when everything is up')."""
        return self._urls[self._index(key)]

    def walk(self, key: bytes):
        """Yield member URLs in ring order starting at ``key``'s owner,
        each member once — the deterministic failover/overflow order."""
        start = self._index(key)
        seen: set[str] = set()
        n = len(self._urls)
        for i in range(n):
            url = self._urls[(start + i) % n]
            if url not in seen:
                seen.add(url)
                yield url

    def _index(self, key: bytes) -> int:
        i = bisect.bisect_right(self._points, _stable_hash(key))
        return i % len(self._points)


class Replica:
    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = True
        self.inflight = 0
        # Per-tier in-flight as the replica itself reports it on /health
        # (the QoS admission ledger), refreshed by every successful probe.
        # Router-attributed inflight above misses direct-to-pod and
        # other-router traffic; this is the replica's own ground truth,
        # and the tier-aware pick tie-break reads it.
        self.tier_inflight: dict = {}
        self.consecutive_failures = 0
        # Traffic-failure bench expiry: a replica broken by proxy failures
        # (connect/stall) may still answer /health 200 — its wedge detector
        # (engine watchdog) is much slower than the router's. Probe success
        # must not restore it before this cooldown, or traffic bounces
        # straight back onto the wedged replica.
        self.benched_until = 0.0


class Router:
    def __init__(self, replica_urls: list[str],
                 health_interval_s: float = 5.0,
                 fail_threshold: int = 2,
                 connect_timeout_s: float = 5.0,
                 stall_timeout_s: float = 60.0,
                 response_timeout_s: float = 300.0,
                 metrics_timeout_s: float = 2.0,
                 connect_retries: int = 2,
                 retry_backoff_s: float = 0.25,
                 bench_cooldown_s: float = 30.0,
                 routing_policy: str = "least-inflight",
                 affinity_prefix_len: int = 32,
                 balance_factor: float = 1.5,
                 ring_vnodes: int = RING_VNODES,
                 trace_timeout_s: float = 5.0,
                 prefill_urls: Optional[list[str]] = None,
                 failover_attempts: int = FAILOVER_ATTEMPTS,
                 qos_tiers: tuple = (),
                 qos_default_tier: Optional[str] = None):
        if routing_policy not in ("least-inflight", "prefix-affinity"):
            raise ValueError(f"unknown routing_policy {routing_policy!r} "
                             "(known: least-inflight, prefix-affinity)")
        if balance_factor < 1.0:
            # c < 1 would bound every replica below the fair share and the
            # walk could never place anything once traffic flows.
            raise ValueError(f"balance_factor {balance_factor} must be >= 1")
        self.replicas = [Replica(u) for u in replica_urls]
        self.routing_policy = routing_policy
        self.affinity_prefix_len = affinity_prefix_len
        self.balance_factor = balance_factor
        self.ring = HashRing([r.url for r in self.replicas],
                             vnodes=ring_vnodes)
        # Disaggregated prefill/decode: a second, phase-dedicated pool.
        # Completion requests are proxied to the MAIN pool (role "decode"
        # when this pool exists, "both" otherwise) with an
        # x-kgct-prefill-url header naming the prefill-pool replica picked
        # by PREFIX-affinity on its own ring — prefill replicas are keyed
        # by prompt prefix (cache locality), decode replicas by session.
        # The decode replica pulls the prefilled KV itself; the router
        # never carries KV bytes.
        self.prefill_replicas = [Replica(u) for u in (prefill_urls or [])]
        self.prefill_ring = (HashRing([r.url for r in self.prefill_replicas],
                                      vnodes=ring_vnodes)
                             if self.prefill_replicas else None)
        # Affinity accounting (rendered on /metrics): a pick is a "hit" when
        # the key landed on its ring owner, an "overflow" (labeled by the
        # owner that was over-bound) when the bounded-load walk moved past
        # it, and a "remap" when the owner was out of rotation entirely.
        self.affinity_requests_total = 0
        self.affinity_hits_total = 0
        self.ring_remaps_total = 0
        self.affinity_overflow_total: dict[str, int] = {
            r.url: 0 for r in self.replicas}
        self.health_interval_s = health_interval_s
        self.fail_threshold = fail_threshold
        self.connect_timeout_s = connect_timeout_s
        # Max seconds between CHUNKS once a response is streaming before the
        # replica is declared stalled (generous: an overloaded engine can
        # pause seconds between tokens; a wedged one goes silent forever).
        self.stall_timeout_s = stall_timeout_s
        # Max seconds to FIRST response bytes (headers). Deliberately much
        # larger than stall_timeout_s: a non-streaming completion sends
        # nothing until the whole generation finishes, and a slow-but-
        # correct generation must not 502 or count toward fail_threshold.
        self.response_timeout_s = response_timeout_s
        self.metrics_timeout_s = metrics_timeout_s
        # Connect-phase failures retry the whole replica set up to this many
        # extra rounds with exponential backoff — rides out the blip where
        # every replica is briefly restarting.
        self.connect_retries = connect_retries
        self.retry_backoff_s = retry_backoff_s
        self.bench_cooldown_s = bench_cooldown_s
        self.retries_total = 0
        self.scrape_errors_total = 0
        # Mid-stream failover accounting: outcome "import" (the successor
        # resumed from a parked migration push), "recompute" (token-replay
        # re-prefill), "failed" (every rung exhausted — the client got the
        # truncated-stream error). Pre-seeded so a fresh scrape renders
        # zeros, never absent series.
        self.failover_attempts = failover_attempts
        self.failovers_total: dict[str, int] = {
            "import": 0, "recompute": 0, "failed": 0}
        self.failover_latency = Histogram(
            "kgct_router_failover_seconds",
            "upstream death to resumed-stream first byte")
        # Fleet tracing: the router's own span stream (pick / connect_retry
        # / ttfb / relay per request id) mirrored into the black-box flight
        # recorder; /debug/trace merges it with replica traces. Bounded
        # per-replica trace fetches (trace_timeout_s) reuse the metrics-
        # scrape straggler discipline: skipped and counted, never hung on.
        self.flight = FlightRecorder()
        self.flight.set_snapshot_source(self._flight_snapshot)
        # enabled=None: the tracer resolves the KGCT_TRACE kill switch
        # itself (one definition, shared with the engine's Observability).
        self.tracer = RequestTracer(capacity=4096, recorder=self.flight)
        self.trace_timeout_s = trace_timeout_s
        self.trace_scrape_errors_total = 0
        # Classification of the LAST _pick (affinity hit/overflow/remap or
        # least-inflight fallback), read by proxy() for the "pick" span —
        # produced inside the seam so the span always matches the counters.
        self._pick_info: dict = {}
        # Tied-least-inflight tie-break: a plain counter starting at 0, so
        # the choice is a pure function of (config, pick sequence) — two
        # routers replaying the same request sequence pick identically, and
        # chaos replays reproduce (the old shared itertools.count iterator
        # had the same values but no seam to assert or reset around).
        self._pick_seq = 0
        # Multi-tenant QoS: the router resolves each request's tier with
        # the SAME order as the replica (config/qos.resolve_tier_name —
        # imported lazily so a tier-less router stays as light as before),
        # propagates the resolution upstream in QOS_TIER_HEADER, and keeps
        # a per-tier in-flight ledger for /health + /metrics (bounded
        # label set: configured tier names only).
        self.qos_tiers = tuple(qos_tiers or ())
        self.qos_default_tier = qos_default_tier
        self.tier_inflight: dict[str, int] = {
            t.name: 0 for t in self.qos_tiers}
        # Tier-aware interactive picks (ROADMAP 3c): priorities for the
        # batch-saturation tie-break in _pick — a higher-priority (more
        # interactive) request prefers, among equally-loaded candidates,
        # the replica whose /health ledger shows the LEAST lower-priority
        # in-flight work (its seats are cheapest to reclaim: the engine's
        # priority preemption evicts batch work, never peers).
        self._tier_priority = {t.name: t.priority for t in self.qos_tiers}
        self._resolve_tier_name = self._tenant_key_of = None
        if self.qos_tiers:
            from ..config.qos import resolve_tier_name, tenant_key_of
            self._resolve_tier_name = resolve_tier_name
            self._tenant_key_of = tenant_key_of
        # Peer reputation over the proxy walk (the router's own instance
        # of the KV wire plane's scoreboard): repeated traffic failures
        # decay a replica's score past the bench machinery's view; a
        # quarantined replica leaves _pick/_prefix_source/_ring_successor
        # until its window lapses, and the first healthy probe after the
        # window is the recovery probe. Quarantine entries render as
        # kgct_peer_quarantines_total{peer} — pre-seeded with every
        # configured replica so the label set is bounded and a fresh
        # scrape shows zeros.
        self.peer_scores = PeerScoreboard()
        for r in self.replicas + self.prefill_replicas:
            self.peer_scores.quarantines.setdefault(r.url, 0)
        self._session: Optional[aiohttp.ClientSession] = None
        self._health_task: Optional[asyncio.Task] = None

    # -- app wiring ----------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self.health)
        app.router.add_get("/v1/models", self.proxy)
        app.router.add_post("/v1/completions", self.proxy)
        app.router.add_post("/v1/chat/completions", self.proxy)
        app.router.add_get("/metrics", self.metrics)
        app.router.add_get("/debug/trace", self.debug_trace)
        app.router.add_get("/debug/flightrecorder", self.debug_flightrecorder)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app: web.Application) -> None:
        # No session-wide sock_read: phase-specific deadlines are applied at
        # the call sites (response_timeout_s for headers, stall_timeout_s
        # between stream chunks) — a blanket read timeout would 502
        # legitimately slow non-streaming generations.
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=None, sock_connect=self.connect_timeout_s))
        # Cold-start probe: without it, a replica that is down RIGHT NOW
        # still receives traffic for up to fail_threshold x interval before
        # the periodic loop notices. One failed startup probe removes it
        # immediately; the loop restores it on recovery.
        await asyncio.gather(
            *(self._check(r, startup=True)
              for r in self.replicas + self.prefill_replicas),
            return_exceptions=True)
        self._health_task = asyncio.create_task(self._health_loop())

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._health_task:
            self._health_task.cancel()
        if self._session:
            await self._session.close()

    # -- health --------------------------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.health_interval_s)
            await asyncio.gather(
                *(self._check(r)
                  for r in self.replicas + self.prefill_replicas),
                return_exceptions=True)
            # Flight-recorder fleet snapshot (per-replica inflight/health)
            # rides the existing periodic loop — no extra timer.
            self.flight.maybe_snapshot()

    def _flight_snapshot(self) -> dict:
        """O(1) state reader for the flight recorder: the router's view of
        fleet load at this instant (attribute reads only)."""
        return {
            "inflight": {r.url: r.inflight for r, _ in self._pools()},
            "healthy": [r.url for r, _ in self._pools() if r.healthy],
            "retries_total": self.retries_total,
        }

    async def _check(self, replica: Replica, startup: bool = False) -> None:
        try:
            async with self._session.get(
                    f"{replica.url}/health",
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                ok = resp.status == 200
                if ok and self.qos_tiers:
                    # Scrape the replica's own per-tier in-flight ledger
                    # off the SAME probe (no extra request): the
                    # tier-aware pick tie-break reads it. Best-effort — a
                    # replica without the field (older build / QoS off)
                    # just keeps an empty dict.
                    try:
                        body = await resp.json()
                        tiers = body.get("qos_tiers")
                        replica.tier_inflight = (
                            {str(k): int(v) for k, v in tiers.items()}
                            if isinstance(tiers, dict) else {})
                    except Exception:
                        pass
        except Exception:
            ok = False
        # Chaos site replica_down: force the probe of replica index
        # ``value`` to fail — the deterministic drain/death simulation the
        # ring-remap chaos test replays (requests owned by the downed
        # replica must move to its ring successor and move back on
        # recovery). The rule's fire budget (after/times/p) is consumed
        # ONLY by the targeted replica's probes: a plain fault_value() here
        # would let every OTHER replica's probe burn the budget first and
        # silently never down the intended one.
        injector = _get_injector()
        if injector is not None:
            rule = injector.rules.get("replica_down")
            if (rule is not None and replica in self.replicas
                    and self.replicas.index(replica) == int(rule.value)
                    and rule.should_fire()):
                logger.warning("KGCT_FAULT replica_down: probe of %s "
                               "forced down", replica.url)
                ok = False
        if ok:
            if time.monotonic() < replica.benched_until:
                # Benched by TRAFFIC failures: a 200 probe proves only that
                # /health answers, not that proxied streams stopped
                # stalling (the engine's own wedge detector is slower than
                # ours) — sit out the cooldown before trusting it again.
                return
            if not self.peer_scores.quarantined(replica.url):
                # Probe-based recovery: the first healthy probe AFTER a
                # lapsed quarantine window restores the replica's score
                # (inside the window this branch is unreachable for score
                # purposes — quarantined() still gates the pick walk).
                self.peer_scores.record_ok(replica.url)
            replica.consecutive_failures = 0
            if not replica.healthy:
                logger.info("replica %s back in rotation", replica.url)
            replica.healthy = True
        else:
            replica.consecutive_failures += 1
            # At startup a single failure is disqualifying (no traffic
            # history argues for the replica); in steady state the threshold
            # rides out transient blips.
            if (replica.healthy
                    and (startup or replica.consecutive_failures
                         >= self.fail_threshold)):
                logger.warning("replica %s marked unhealthy%s", replica.url,
                               " (startup probe)" if startup else "")
                replica.healthy = False

    def _pools(self) -> list[tuple[Replica, str]]:
        """Every replica the router owns, with its pool role: the main
        pool serves decode streams ("decode" when a prefill pool exists,
        the pre-disaggregation "both" otherwise), the prefill pool serves
        KV-handoff exports. One scrape separates the pools by the role
        label."""
        main_role = "decode" if self.prefill_replicas else "both"
        return ([(r, main_role) for r in self.replicas]
                + [(r, "prefill") for r in self.prefill_replicas])

    async def health(self, request: web.Request) -> web.Response:
        healthy = [r.url for r in self.replicas if r.healthy]
        status = 200 if healthy else 503
        body = {"status": "ok" if healthy else "no healthy replicas",
                "replicas": {r.url: {"healthy": r.healthy,
                                     "inflight": r.inflight,
                                     "role": role}
                             for r, role in self._pools()}}
        if self.qos_tiers:
            # Per-tier in-flight (fleet view): which tenant class is
            # loading the pool right now; absent when QoS is off.
            body["qos_tiers"] = dict(self.tier_inflight)
        return web.json_response(body, status=status)

    async def metrics(self, request: web.Request) -> web.Response:
        # Per-replica gauges carry the POOL role (prefill|decode|both) so
        # one scrape separates prefill-pool from decode-pool health under
        # disaggregated serving; a non-disaggregated fleet renders the
        # pre-existing "both" everywhere.
        pools = self._pools()
        lines = ["# TYPE kgct_router_replica_healthy gauge"]
        lines += [f'kgct_router_replica_healthy{{replica="{r.url}",'
                  f'role="{role}"}} {int(r.healthy)}' for r, role in pools]
        lines.append("# TYPE kgct_router_replica_inflight gauge")
        lines += [f'kgct_router_replica_inflight{{replica="{r.url}",'
                  f'role="{role}"}} {r.inflight}' for r, role in pools]
        if self.tier_inflight:
            # Multi-tenant QoS: per-tier in-flight through this router —
            # bounded label set (configured tier names), zeros from the
            # first scrape, absent entirely when QoS is off.
            lines.append("# TYPE kgct_router_tier_inflight gauge")
            lines += [f'kgct_router_tier_inflight{{tier="{n}"}} '
                      f"{self.tier_inflight[n]}"
                      for n in sorted(self.tier_inflight)]
        lines += ["# TYPE kgct_router_retries_total counter",
                  f"kgct_router_retries_total {self.retries_total}"]
        lines.append("# TYPE kgct_failovers_total counter")
        lines += [f'kgct_failovers_total{{outcome="{oc}"}} {n}'
                  for oc, n in sorted(self.failovers_total.items())]
        lines += self.failover_latency.render()
        # Routing-policy surface: which policy is live (info-style gauge)
        # plus the affinity accounting. All zeros-safe — a fresh scrape of a
        # least-inflight router renders every series with 0, never nan/absent
        # (dashboards need no existence check).
        reqs = self.affinity_requests_total
        lines += [
            "# TYPE kgct_router_policy gauge",
            f'kgct_router_policy{{policy="{self.routing_policy}"}} 1',
            "# TYPE kgct_router_affinity_requests_total counter",
            f"kgct_router_affinity_requests_total {reqs}",
            "# TYPE kgct_router_affinity_hits_total counter",
            f"kgct_router_affinity_hits_total {self.affinity_hits_total}",
            "# TYPE kgct_router_affinity_hit_ratio gauge",
            "kgct_router_affinity_hit_ratio "
            f"{self.affinity_hits_total / reqs if reqs else 0.0}",
            "# TYPE kgct_router_ring_remaps_total counter",
            f"kgct_router_ring_remaps_total {self.ring_remaps_total}",
            "# TYPE kgct_router_affinity_overflow_total counter",
        ]
        lines += [f'kgct_router_affinity_overflow_total{{replica="{r.url}"}} '
                  f"{self.affinity_overflow_total.get(r.url, 0)}"
                  for r in self.replicas]
        # Aggregate each healthy replica's engine metrics behind the single
        # front door (one scrape target for the whole DP group), labelled by
        # replica so series do not collide. Each per-replica fetch is bounded
        # (metrics_timeout_s): one stalled replica must not hang the whole
        # scrape — stragglers are skipped and counted instead.
        scraped = [r for r, _ in pools if r.healthy]
        fetched = await asyncio.gather(
            *(self._fetch_metrics(r) for r in scraped),
            return_exceptions=True)
        self.scrape_errors_total += sum(
            1 for res in fetched if isinstance(res, BaseException))
        lines += ["# TYPE kgct_router_metrics_scrape_errors_total counter",
                  "kgct_router_metrics_scrape_errors_total "
                  f"{self.scrape_errors_total}",
                  "# TYPE kgct_router_trace_scrape_errors_total counter",
                  "kgct_router_trace_scrape_errors_total "
                  f"{self.trace_scrape_errors_total}"]
        # Fleet locality readout: fold each replica's scraped prefix-cache
        # hit ratio and swapped-sequence count into router-OWNED labeled
        # gauges, so "is affinity concentrating locality" is one scrape of
        # one target. Zeros/absent-safe: every replica gets a sample — 0.0
        # when it is unhealthy, was skipped as a straggler, or its engine
        # predates the series — a fresh scrape is nan-free by construction.
        locality = {r.url: {"kgct_prefix_cache_hit_ratio": 0.0,
                            "kgct_num_swapped": 0.0}
                    for r, _ in pools}
        for replica, res in zip(scraped, fetched):
            if isinstance(res, BaseException):
                continue
            for family, is_type, line in res:
                if is_type or family not in ("kgct_prefix_cache_hit_ratio",
                                             "kgct_num_swapped"):
                    continue
                base = line.partition("{")[0]
                if base not in locality[replica.url]:
                    continue    # histogram-style child of another family
                try:
                    locality[replica.url][base] = float(line.rpartition(
                        " ")[2])
                except ValueError:
                    pass        # malformed upstream sample: keep the zero
        for name in ("kgct_prefix_cache_hit_ratio", "kgct_num_swapped"):
            lines.append(f"# TYPE kgct_router_replica_{name.removeprefix('kgct_')} gauge")
            lines += [
                f'kgct_router_replica_{name.removeprefix("kgct_")}'
                f'{{replica="{r.url}",role="{role}"}} '
                f'{locality[r.url][name]}'
                for r, role in pools]
        # Regroup by metric family: the text exposition format requires ONE
        # TYPE line per family with ALL its samples contiguous — appending
        # replicas' expositions sequentially interleaves families and strict
        # parsers (promtool/OpenMetrics) reject the whole scrape.
        families: dict[str, dict] = {}
        for res in fetched:
            if isinstance(res, BaseException):
                continue
            for family, is_type, line in res:
                fam = families.setdefault(family, {"type": None, "samples": []})
                if is_type:
                    if fam["type"] is None:
                        fam["type"] = line
                else:
                    fam["samples"].append(line)
        # Peer quarantine entries: the router's OWN scoreboard (label set
        # bounded to configured replicas, zeros from the first scrape)
        # shares a family name with each engine's replica-side board — one
        # TYPE line, all samples contiguous, scraped samples relabelled.
        scraped_quar = families.pop("kgct_peer_quarantines_total", None)
        lines.append("# TYPE kgct_peer_quarantines_total counter")
        lines += [f'kgct_peer_quarantines_total{{peer="{peer}"}} '
                  f"{self.peer_scores.quarantines[peer]}"
                  for peer in sorted(self.peer_scores.quarantines)]
        if scraped_quar is not None:
            lines.extend(scraped_quar["samples"])
        for fam in families.values():
            if fam["type"] is not None:
                lines.append(fam["type"])
            lines.extend(fam["samples"])
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _fetch_metrics(self, replica: Replica):
        """Returns (family, is_type, line) triples with samples relabelled by
        replica. Family attribution follows the exposition's own ordering —
        a TYPE line opens a family and subsequent samples whose base name is
        the family (or family + ``_suffix``, the summary/histogram
        ``_sum``/``_count``/``_bucket`` children) belong to it."""
        async with self._session.get(
                f"{replica.url}/metrics",
                timeout=aiohttp.ClientTimeout(total=self.metrics_timeout_s)
                ) as resp:
            text = await resp.text()
        label = f'replica="{replica.url}"'
        out = []
        current = None
        for line in text.splitlines():
            if not line or line.startswith("#"):
                if line.startswith("# TYPE"):
                    parts = line.split()
                    current = parts[2] if len(parts) > 2 else line
                    out.append((current, True, line))
                continue
            name, _, rest = line.partition(" ")
            base = name.partition("{")[0]
            family = (current if current and
                      (base == current or base.startswith(current + "_"))
                      else base)
            if "{" in name:
                labels = name.partition("{")[2]
                out.append((family, False, f"{base}{{{label},{labels} {rest}"))
            else:
                out.append((family, False, f"{base}{{{label}}} {rest}"))
        return out

    # -- fleet tracing -------------------------------------------------------

    async def debug_trace(self, request: web.Request) -> web.Response:
        """ONE Perfetto timeline for the whole fleet: the router's own span
        stream (pid 1) merged with each healthy replica's ``/debug/trace``
        (one pid per replica), re-based onto a common clock via the
        ``kgctT0Unix`` anchors. A request that crossed router -> replica ->
        engine step phases renders as correlated spans across the process
        tracks, keyed by the router-minted request id. Each per-replica
        fetch is bounded (``trace_timeout_s``) — a stalled replica is
        skipped and counted in kgct_router_trace_scrape_errors_total, same
        discipline as the metrics scrape."""
        docs = [("kgct-router", self.tracer.export_perfetto())]
        scraped = [r for r, _ in self._pools() if r.healthy]
        fetched = await asyncio.gather(
            *(self._fetch_trace(r) for r in scraped),
            return_exceptions=True)
        for replica, res in zip(scraped, fetched):
            if isinstance(res, BaseException) or not isinstance(res, dict):
                self.trace_scrape_errors_total += 1
                continue
            docs.append((f"kgct-engine {replica.url}", res))
        return web.json_response(merge_perfetto(docs))

    async def _fetch_trace(self, replica: Replica) -> dict:
        async with self._session.get(
                f"{replica.url}/debug/trace",
                timeout=aiohttp.ClientTimeout(total=self.trace_timeout_s)
                ) as resp:
            return await resp.json()

    async def debug_flightrecorder(self, request: web.Request) -> web.Response:
        """The router's black-box ring: recent spans + periodic fleet
        snapshots (per-replica inflight/health)."""
        return web.json_response(self.flight.export())

    # -- proxying ------------------------------------------------------------

    def _pick(self, exclude: Optional[set] = None,
              include_unhealthy: bool = False,
              affinity_key: Optional[bytes] = None,
              pool: Optional[list] = None,
              ring: Optional[HashRing] = None,
              pick_tier: Optional[str] = None) -> Optional[Replica]:
        """The ONE replica-selection seam (every proxy attempt, including
        retry-with-exclude, desperation rounds, and the prefill-pool pick
        of disaggregated serving, calls here — KGCT011).

        ``pick_tier`` (the request's RESOLVED QoS tier) engages the
        tier-aware tie-break on the least-inflight fallback: for a pick of
        a non-lowest tier, candidates tied on total inflight are further
        narrowed to those whose /health-scraped ledger shows the least
        strictly-lower-priority in-flight work — a batch-saturated replica
        is deprioritized for interactive picks while equally-loaded
        interactive-only replicas keep the legacy rotation. Tier None (QoS
        off, or a lowest-tier pick) is byte-identical to the legacy
        tie-break.

        ``affinity_key`` engages the prefix-affinity policy: walk the ring
        from the key's owner, skipping out-of-rotation replicas, and take
        the first whose load stays inside the CHWBL bound
        ``ceil(balance_factor * (total_inflight + 1) / n_candidates)``.
        All-over-bound (a bound < 1 is impossible, so this means real
        saturation) falls through to least-inflight over the same
        candidates — the policy degrades, it never refuses.

        ``pool``/``ring`` select a phase-dedicated pool instead of the main
        one (the disaggregated PREFILL pool). A non-main pool walks its
        ring whenever a key exists REGARDLESS of the configured policy —
        prefill replicas are keyed by prompt prefix by construction — and
        its picks stay out of the affinity counters (which account the
        client-facing pool)."""
        main = pool is None
        replicas = self.replicas if pool is None else pool
        ring = self.ring if ring is None else ring
        healthy = [r for r in replicas
                   if (r.healthy or include_unhealthy)
                   and (include_unhealthy
                        or not self.peer_scores.quarantined(r.url))
                   and (not exclude or r.url not in exclude)]
        self._pick_info = {"policy": self.routing_policy, "pick": "none"}
        if not healthy:
            return None
        if (affinity_key is not None
                and (self.routing_policy == "prefix-affinity" or not main)):
            candidates = {r.url: r for r in healthy}
            bound = math.ceil(
                self.balance_factor
                * (sum(r.inflight for r in healthy) + 1) / len(healthy))
            owner_url = ring.owner(affinity_key)
            if main:
                self.affinity_requests_total += 1
                if owner_url not in candidates:
                    # Owner unhealthy/benched/excluded: its keys remap to
                    # ring successors until it returns (deterministic, and
                    # only ITS keys move).
                    self.ring_remaps_total += 1
            for url in ring.walk(affinity_key):
                replica = candidates.get(url)
                if replica is None:
                    continue
                if replica.inflight + 1 <= bound:
                    if url == owner_url:
                        if main:
                            self.affinity_hits_total += 1
                        self._pick_info["pick"] = "affinity_hit"
                    elif owner_url in candidates:
                        # Owner was available but over-bound: the hot-key
                        # spillover the balance factor exists to allow.
                        if main:
                            self.affinity_overflow_total[owner_url] = (
                                self.affinity_overflow_total.get(
                                    owner_url, 0) + 1)
                        self._pick_info["pick"] = "affinity_overflow"
                        self._pick_info["owner"] = owner_url
                    else:
                        self._pick_info["pick"] = "affinity_remap"
                        self._pick_info["owner"] = owner_url
                    return replica
            # Every candidate over-bound: saturation, not a routing failure.
        least = min(r.inflight for r in healthy)
        tied = [r for r in healthy if r.inflight == least]
        if pick_tier is not None and len(tied) > 1:
            tied = self._tier_tie_break(tied, pick_tier)
        seq = self._pick_seq
        self._pick_seq += 1
        self._pick_info["pick"] = "least_inflight"
        return tied[seq % len(tied)]

    def _tier_tie_break(self, tied: list, pick_tier: str) -> list:
        """Among total-inflight-tied candidates, keep those with the least
        strictly-lower-priority in-flight work (the replicas' own /health
        ledgers). Only engages for non-lowest-tier picks — a batch pick
        has no lower tier to avoid, and must keep the legacy rotation."""
        prio = self._tier_priority.get(pick_tier)
        if prio is None:
            return tied
        lower = [name for name, p in self._tier_priority.items()
                 if p < prio]
        if not lower:
            return tied
        load = {r.url: sum(int(r.tier_inflight.get(name, 0))
                           for name in lower) for r in tied}
        floor = min(load.values())
        kept = [r for r in tied if load[r.url] == floor]
        if len(kept) < len(tied):
            self._pick_info["tier_deprioritized"] = len(tied) - len(kept)
        return kept

    def _affinity_key(self, body: bytes, force: bool = False) -> Optional[bytes]:
        """Derive the routing key from an already-buffered request body —
        the proxy reads the full body before forwarding anyway (it may
        re-send it on connect-phase failover), so the peek adds no latency
        and never touches the response streaming path.

        Precedence: explicit stickiness (``session_id``, then OpenAI's
        ``user``) beats the prompt prefix — a session's later turns carry a
        GROWING prompt, and only the explicit id keeps them on the replica
        whose cache holds the earlier turns. Prompt prefix: the first
        ``affinity_prefix_len`` ids of a token-array prompt, or the first
        ``4 * affinity_prefix_len`` UTF-8 bytes of a text prompt / chat
        messages serialization (~4 bytes per token, so both spellings key
        on a comparable prefix window). None (no key derivable) routes
        least-inflight.

        ``force`` derives the key regardless of the configured policy —
        the disaggregated PREFILL pool is always prefix-keyed, even when
        the client-facing pool balances least-inflight."""
        if self.routing_policy != "prefix-affinity" and not force:
            return None
        return self._affinity_key_from_obj(self._parse_json_dict(body))

    @staticmethod
    def _parse_json_dict(body: bytes) -> Optional[dict]:
        """Parse an already-buffered request body into the JSON object
        every routing peek keys off — parsed ONCE per request in proxy()
        and shared, so a long-prompt body is never scanned twice on the
        single-threaded event loop. None for empty/unparseable/non-object
        bodies (the replica's fast 400 to give, not the router's)."""
        if not body:
            return None
        try:
            obj = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        return obj if isinstance(obj, dict) else None

    def _affinity_key_from_obj(self, obj: Optional[dict]) -> Optional[bytes]:
        if obj is None:
            return None
        for field in ("session_id", "user"):
            val = obj.get(field)
            if isinstance(val, (str, int)) and not isinstance(val, bool) \
                    and val != "":
                return f"sticky:{field}:{val}".encode()
        text_window = 4 * self.affinity_prefix_len
        prompt = obj.get("prompt")
        if isinstance(prompt, str):
            return b"text:" + prompt.encode("utf-8")[:text_window]
        if isinstance(prompt, list) and prompt:
            if len(prompt) == 1 and isinstance(prompt[0], str):
                return b"text:" + prompt[0].encode("utf-8")[:text_window]
            if all(isinstance(t, int) for t in
                   prompt[:self.affinity_prefix_len]):
                ids = ",".join(str(t)
                               for t in prompt[:self.affinity_prefix_len])
                return f"tokens:{ids}".encode()
        messages = obj.get("messages")
        if isinstance(messages, list) and messages:
            try:
                ser = json.dumps(messages, sort_keys=True)
            except (TypeError, ValueError):
                return None
            return b"chat:" + ser.encode("utf-8")[:text_window]
        return None

    @staticmethod
    def _handoff_eligible(obj: Optional[dict]) -> bool:
        """Whether this request can consume a KV handoff on the decode
        side. ``n``/``best_of`` > 1 requests fan out through the replica's
        ``_run_n`` BEFORE its handoff block — no pull ever happens — so a
        prefill pick would hold a phantom pull slot for the request's
        whole lifetime and skew the prefill ring's bounded-load math.
        Anything not positively multi-sequence (including bodies
        ``_parse_json_dict`` rejected — the replica's fast 400 to give)
        stays eligible: same behavior as today, and a slot held across a
        400 is noise."""
        if obj is None:
            return True
        try:
            n = 1 if obj.get("n") is None else int(obj["n"])
            best_of = n if obj.get("best_of") is None else int(obj["best_of"])
        except (TypeError, ValueError):
            return True
        return n <= 1 and best_of <= 1

    async def proxy(self, request: web.Request) -> web.StreamResponse:
        """Reverse-proxy with failover.

        Only CONNECT-phase failures (replica down/unreachable) fail over to
        the next healthy replica — a request the upstream already received
        may be mid-generation there, and re-sending it would silently double
        device work under exactly the overload that causes resets. When
        every healthy replica fails the connect phase, the whole set is
        retried up to ``connect_retries`` more rounds with exponential
        backoff. Upstream errors after the body was delivered return 502;
        after streaming to the client started, the stream is terminated
        (truncation is the signal) and the stall/death circuit-breaks the
        replica. Client-side disconnects never count against the replica.

        Correlation id: an inbound ``x-kgct-request-id`` is honored (header
        contract: bounded charset/length, else a fresh id is minted), sent
        upstream — the replica adopts it as its engine request id — and
        echoed on EVERY response including 429/502/503, so a failed request
        in a client log joins the router spans, the replica trace, and the
        JSON log records on one id."""
        body = await request.read()
        rid = valid_request_id(request.headers.get(REQUEST_ID_HEADER))
        if rid is None:
            rid = "req-" + uuid.uuid4().hex[:20]
        # Parse the body ONCE: the main-pool affinity key and both
        # prefill-pool peeks below share the object (configs needing
        # neither never parse at all).
        disagg_post = bool(self.prefill_replicas
                           and request.method == "POST"
                           and request.path.endswith("/completions"))
        # Session survivability needs the parsed body too (stream flag +
        # the resume re-dispatch payload) whenever the fleet has a peer a
        # stream could fail over to. The byte-level pre-filter keeps the
        # common non-streaming request off the json.loads hot path: only
        # streams fail over, and a body without the key cannot be one.
        survivable_post = bool(len(self.replicas) > 1
                               and request.method == "POST"
                               and request.path.endswith("/completions")
                               and b'"stream"' in body)
        # QoS tier resolution needs the tenant key (session_id/user) from
        # the body — same single parse as every other peek.
        qos_post = bool(self.qos_tiers
                        and request.method == "POST"
                        and request.path.endswith("/completions"))
        obj = self._parse_json_dict(body) \
            if (self.routing_policy == "prefix-affinity" or disagg_post
                or survivable_post or qos_post) \
            else None
        tier = qos_hdr = None
        if qos_post:
            tier, qos_hdr = self._qos_resolve(request, obj)
        akey = self._affinity_key_from_obj(obj) \
            if self.routing_policy == "prefix-affinity" else None
        self.tracer.emit("arrival", rid, path=request.path,
                         policy=self.routing_policy, bytes=len(body))
        # Disaggregated serving: pick the prefill-pool replica ONCE per
        # request (prefix-affinity on the prefill ring — always keyed,
        # whatever the main policy) and name it in the forwarded header;
        # the decode replica pulls the KV itself. No healthy prefill
        # replica -> no header -> the decode replica prefills locally.
        pr = None
        if disagg_post and self._handoff_eligible(obj):
            pkey = akey if self.routing_policy == "prefix-affinity" \
                else self._affinity_key_from_obj(obj)
            pr = self._pick(affinity_key=pkey, pool=self.prefill_replicas,
                            ring=self.prefill_ring)
            pf_info = dict(self._pick_info)
            if pr is not None:
                self.tracer.emit("pick", rid, replica=pr.url,
                                 pool="prefill", **pf_info)
        # Per-tier in-flight ledger (QoS): brackets the whole proxied
        # lifetime, streaming included — the fleet-level view of which
        # tenant class is loading the pool.
        if tier is not None:
            self.tier_inflight[tier] += 1
        try:
            if pr is None:
                return await self._forward(request, body, rid, akey, None,
                                           obj=obj, qos_hdr=qos_hdr,
                                           tier=tier)
            # The handoff pull slot is outstanding on this prefill replica
            # for the request's lifetime — without the count the prefill
            # pool's bounded-load overflow could never trigger (every
            # prefill Replica would read inflight 0 forever) and a hot
            # prefix would pin 100% of handoffs to one replica, each
            # holding a bounded pull slot, while the rest of the pool
            # idled. The request span over-estimates the pull window
            # (decode rides along), which only makes spillover MORE eager
            # under pile-up — the safe direction.
            pr.inflight += 1
            try:
                return await self._forward(request, body, rid, akey, pr.url,
                                           obj=obj, qos_hdr=qos_hdr,
                                           tier=tier)
            finally:
                pr.inflight -= 1
        finally:
            if tier is not None:
                self.tier_inflight[tier] -= 1

    def _qos_resolve(self, request: web.Request, obj: Optional[dict]
                     ) -> tuple[Optional[str], Optional[str]]:
        """(resolved tier, header value to forward) — the router-side half
        of the one resolution order (config/qos.resolve_tier_name): valid
        inbound header > tenant-key user pin > default. An INVALID inbound
        header resolves nothing and is forwarded untouched — the replica
        owns body/header validation and 400s loudly; the router must not
        silently re-class a typo'd tier."""
        tier, err = self._resolve_tier_name(
            self.qos_tiers, self.qos_default_tier,
            header=request.headers.get(QOS_TIER_HEADER),
            tenant_key=self._tenant_key_of(obj))
        if err is not None:
            return None, None
        return tier, tier

    def _prefix_source(self, pick_info: dict,
                       chosen_url: str) -> Optional[str]:
        """The PREFIX_SOURCE_HEADER value for a pick that missed its
        affinity owner, or None. Only a LIVE owner is worth naming: an
        over-bound owner (overflow) is healthy by construction; a
        remapped owner may merely be excluded by this request's retry
        walk — but one that is down or benched would cost the chosen
        replica a doomed connect before its pull degrades, worse than
        just recomputing."""
        if pick_info.get("pick") not in ("affinity_overflow",
                                         "affinity_remap"):
            return None
        owner_url = pick_info.get("owner")
        if not owner_url or owner_url == chosen_url:
            return None
        for r in self.replicas:
            if r.url == owner_url:
                if (r.healthy and time.monotonic() >= r.benched_until
                        and not self.peer_scores.quarantined(r.url)):
                    return owner_url
                return None
        return None

    def _ring_successor(self, key: bytes, exclude: set) -> Optional[str]:
        """First healthy main-pool replica on the ring walk from ``key``
        that is not in ``exclude`` — the deterministic migrate-push /
        failover target. The draining replica pushes a stream's KV to this
        URL (the router names it in MIGRATE_URL_HEADER at dispatch), and
        the failover re-dispatch walks the SAME ring, so the resume lands
        where the parked state lives."""
        byurl = {r.url: r for r in self.replicas}
        for url in self.ring.walk(key):
            replica = byurl.get(url)
            if replica is not None and replica.healthy \
                    and not self.peer_scores.quarantined(url) \
                    and url not in exclude:
                return url
        return None

    async def _forward(self, request: web.Request, body: bytes, rid: str,
                       akey: Optional[bytes],
                       prefill_hdr: Optional[str],
                       obj: Optional[dict] = None,
                       qos_hdr: Optional[str] = None,
                       tier: Optional[str] = None) -> web.StreamResponse:
        """The failover forwarding loop of :meth:`proxy`, split out so the
        prefill-slot accounting brackets it in one try/finally whatever
        path it returns through. ``obj`` (the parsed body) enables
        MID-STREAM failover for SSE completions: the relay parses frames
        (stripping the replica's kgct_token_ids ledger), and an upstream
        that dies before [DONE] is transparently re-dispatched to a ring
        successor via /internal/resume with the relayed tokens as forced
        context — the client sees one uninterrupted stream."""
        tried: set[str] = set()
        last_err: Optional[Exception] = None
        connect_failed = False
        rounds = 0
        # Failover key: the affinity key when one exists (the same walk
        # the pick used), else a request-id-derived key — deterministic
        # either way, so push target and failover target agree.
        mig_key = akey if akey is not None else f"failover:{rid}".encode()
        failover_ok = bool(len(self.replicas) > 1 and isinstance(obj, dict)
                           and obj.get("stream")
                           and request.method == "POST"
                           and request.path.endswith("/completions"))
        while True:
            # Retry rounds (rounds > 0) ignore the healthy flag: the connect
            # failures that triggered the retry are exactly what benched the
            # replicas (fail_threshold), and a retry restricted to healthy
            # ones would find nothing and give up — defeating its purpose of
            # riding out a restart blip. Nothing reached any upstream, so a
            # desperation probe of benched replicas is safe.
            replica = self._pick(exclude=tried,
                                 include_unhealthy=rounds > 0,
                                 affinity_key=akey, pick_tier=tier)
            # Consume the pick classification SYNCHRONOUSLY (no await may
            # sit between the _pick call and this copy): _pick overwrites
            # the shared attribute on its next call, and in an async server
            # a deferred read would attribute one request's affinity
            # hit/overflow/remap to another request's span.
            pick_info = dict(self._pick_info)
            if replica is None:
                # Every candidate this round failed at connect: nothing was
                # sent anywhere, so a bounded backed-off re-probe of the
                # full set is safe (replicas restart in seconds under k8s).
                if connect_failed and rounds < self.connect_retries and tried:
                    await asyncio.sleep(
                        self.retry_backoff_s * (2 ** rounds))
                    rounds += 1
                    tried.clear()
                    connect_failed = False
                    continue
                break
            tried.add(replica.url)
            self.tracer.emit("pick", rid, replica=replica.url,
                             attempt=len(tried), round=rounds, **pick_info)
            replica.inflight += 1
            try:
                try:
                    if _inject_fault("router_connect"):
                        raise ConnectionRefusedError(
                            "KGCT_FAULT router_connect")
                    stripped = {REQUEST_ID_HEADER, PREFILL_URL_HEADER,
                                MIGRATE_URL_HEADER, PREFIX_SOURCE_HEADER}
                    if qos_hdr is not None:
                        # Propagate the ROUTER-resolved tier: both layers
                        # then attribute this request identically (an
                        # unresolvable inbound header passes through for
                        # the replica's loud 400 instead).
                        stripped.add(QOS_TIER_HEADER)
                    fwd_headers = {
                        k: v for k, v in request.headers.items()
                        if k.lower() not in HOP_HEADERS
                        and k.lower() not in stripped}
                    # The replica adopts this as its engine request id, so
                    # its lifecycle trace correlates with the router spans.
                    fwd_headers[REQUEST_ID_HEADER] = rid
                    if qos_hdr is not None:
                        fwd_headers[QOS_TIER_HEADER] = qos_hdr
                    if prefill_hdr is not None:
                        # Router-owned (client values stripped above): the
                        # decode replica pulls prefilled KV from here.
                        fwd_headers[PREFILL_URL_HEADER] = prefill_hdr
                    psrc = self._prefix_source(pick_info, replica.url)
                    if psrc is not None:
                        # Fleet-wide prefix cache: the pick could not land
                        # on the affinity owner (over-bound or out of this
                        # round's rotation) — name the owner so the chosen
                        # replica can PULL its cached prefix instead of
                        # recomputing it (/internal/fetch_prefix; the
                        # replica's roofline gate prices the pull and any
                        # failure degrades to local recompute). Router-
                        # owned, like the prefill url: client values are
                        # stripped above.
                        fwd_headers[PREFIX_SOURCE_HEADER] = psrc
                    mig_url = None
                    if failover_ok:
                        # Name the drain-push target (ring successor of the
                        # serving replica): a SIGTERM on the upstream
                        # live-migrates this stream's KV there, and our own
                        # failover walk below re-dispatches to the same
                        # place. Header presence also opts the replica into
                        # embedding the per-frame token ledger.
                        mig_url = self._ring_successor(mig_key,
                                                       {replica.url})
                        if mig_url is not None:
                            fwd_headers[MIGRATE_URL_HEADER] = mig_url
                    t_attempt = time.monotonic()
                    upstream_cm = self._session.request(
                        request.method, f"{replica.url}{request.path_qs}",
                        data=body if body else None, headers=fwd_headers)
                    # Headers deadline: a replica that accepted the request
                    # and then never responds at all is wedged — but the
                    # bound is the generous response_timeout_s, because a
                    # non-streaming completion legitimately sends nothing
                    # until the whole generation finishes.
                    upstream = await asyncio.wait_for(
                        upstream_cm.__aenter__(), self.response_timeout_s)
                    self.tracer.emit(
                        "ttfb", rid, replica=replica.url,
                        status=upstream.status,
                        ms=round((time.monotonic() - t_attempt) * 1e3, 2))
                except CONNECT_PHASE_ERRORS as e:
                    # TCP connect failed or timed out: nothing reached the
                    # upstream — safe to fail over.
                    last_err = e
                    connect_failed = True
                    self.retries_total += 1
                    self.tracer.emit("connect_retry", rid,
                                     replica=replica.url, error=str(e))
                    self._count_failure(replica, e, request_id=rid)
                    continue
                except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                    # Request sent (at least partially) but no response —
                    # including a replica that accepted the body then went
                    # silent past stall_timeout_s: the upstream may already
                    # be processing it — do NOT re-send.
                    last_err = e
                    self._count_failure(replica, e, request_id=rid)
                    break
                try:
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in HOP_HEADERS:
                            resp.headers[k] = v
                    # Prefer the replica's echoed id (already copied above):
                    # its engine may have SUFFIXED a duplicate (rid+dup-N),
                    # and the header must name the id the engine trace and
                    # response body actually use. A non-kgct upstream that
                    # echoed nothing gets our mint.
                    if REQUEST_ID_HEADER not in resp.headers:
                        resp.headers[REQUEST_ID_HEADER] = rid
                    await resp.prepare(request)
                    relayed = 0
                    # Parse-mode relay: a migration-capable SSE stream is
                    # framed so the token ledger can be kept (and stripped)
                    # and truncation-before-[DONE] detected; everything
                    # else relays raw chunks, byte-identical to before.
                    relay = None
                    if (mig_url is not None and upstream.status == 200
                            and upstream.headers.get(
                                "Content-Type", "").startswith(
                                "text/event-stream")):
                        relay = _SSERelay()
                    while True:
                        try:
                            if _inject_fault("replica_hang"):
                                raise asyncio.TimeoutError(
                                    "KGCT_FAULT replica_hang")
                            if relay is not None and _inject_fault(
                                    "replica_kill_midstream"):
                                # Chaos: the upstream socket is severed
                                # after N relayed chunks (rule param
                                # ``after``) — the deterministic
                                # mid-stream death the failover exists
                                # for.
                                raise aiohttp.ClientPayloadError(
                                    "KGCT_FAULT replica_kill_midstream: "
                                    "upstream socket severed")
                            # Per-chunk stall deadline: once streaming, a
                            # healthy engine emits tokens continuously —
                            # stall_timeout_s of silence means the replica
                            # hung mid-generation.
                            chunk = await asyncio.wait_for(
                                upstream.content.readany(),
                                self.stall_timeout_s)
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError) as e:
                            # Upstream died or stalled mid-stream:
                            # circuit-break the replica. A migration-
                            # capable stream re-dispatches to a ring
                            # successor (the resume ladder); otherwise the
                            # committed client stream is terminated
                            # (truncation is the signal).
                            self._count_failure(replica, e, request_id=rid)
                            if relay is not None and not relay.done:
                                upstream.close()
                                await self._failover_midstream(
                                    request, resp, rid, obj, relay,
                                    mig_key, {replica.url}, err=e)
                                return resp
                            self.tracer.emit("abort", rid,
                                             reason="upstream_stall",
                                             error=str(e), bytes=relayed)
                            with contextlib.suppress(Exception):
                                await resp.write_eof()
                            return resp
                        if not chunk:
                            if relay is not None and not relay.done:
                                # EOF before [DONE]: a drain severed the
                                # relay after pushing the stream's KV (or
                                # the replica died cleanly) — same resume
                                # ladder as an errored read.
                                err = RuntimeError(
                                    "upstream stream ended before [DONE]")
                                self._count_failure(replica, err,
                                                    request_id=rid)
                                upstream.close()
                                await self._failover_midstream(
                                    request, resp, rid, obj, relay,
                                    mig_key, {replica.url}, err=err)
                                return resp
                            break
                        out = chunk if relay is None else relay.feed(chunk)
                        try:
                            if out:
                                await resp.write(out)
                                relayed += len(out)
                        except (ConnectionError, aiohttp.ClientError):
                            # CLIENT went away — not the replica's fault; no
                            # failure accounting.
                            self.tracer.emit("abort", rid,
                                             reason="client_disconnect",
                                             bytes=relayed)
                            return resp
                    await resp.write_eof()
                    self.tracer.emit("relay", rid, bytes=relayed)
                    self.tracer.emit("finish", rid, status=upstream.status,
                                     replica=replica.url)
                    return resp
                finally:
                    await upstream_cm.__aexit__(None, None, None)
            finally:
                replica.inflight -= 1
        if last_err is not None:
            self.tracer.emit("abort", rid, reason="upstream_error",
                             error=str(last_err))
            logger.warning("proxy failed after %d replicas: %s", len(tried),
                           last_err, extra={"request_id": rid})
            resp = _proxy_error(502, f"upstream error: {last_err}",
                                retry_after_s=1)
            resp.headers[REQUEST_ID_HEADER] = rid
            return resp
        self.tracer.emit("abort", rid, reason="no_healthy_replicas")
        logger.warning("no healthy replicas for request",
                       extra={"request_id": rid})
        resp = _proxy_error(
            503, "no healthy replicas; retry shortly",
            retry_after_s=self._retry_after_s())
        resp.headers[REQUEST_ID_HEADER] = rid
        return resp

    def _retry_after_s(self) -> int:
        """Retry-After for a no-healthy 503: the soonest instant any
        replica can return to rotation — the minimum remaining
        bench/quarantine window across the pool — so a well-behaved
        client backs off exactly as long as the shed will last (the
        PR-2 admission-shed contract). Replicas that are merely
        probe-down fall back to the health interval."""
        now = time.monotonic()
        waits = []
        for r in self.replicas:
            wait = max(r.benched_until - now,
                       self.peer_scores.retry_after_s(r.url))
            # A merely probe-down replica (no active window) can return
            # on the next health tick.
            waits.append(wait if wait > 0 else self.health_interval_s)
        soonest = min(waits) if waits else self.health_interval_s
        return max(int(math.ceil(soonest)), 1)

    async def _failover_midstream(self, request: web.Request,
                                  resp: web.StreamResponse, rid: str,
                                  obj: dict, relay: _SSERelay,
                                  key: bytes, exclude: set,
                                  err: Optional[Exception] = None) -> bool:
        """Transparent mid-stream failover: re-dispatch a broken SSE relay
        to ring successors via ``POST /internal/resume`` (original body +
        the relayed-token ledger) and splice the resumed stream onto the
        already-committed client response. Bounded attempts; every rung
        exhausted ends the stream with a CLEAN truncated-stream error
        frame carrying the request id — degraded, attributed, never a
        hang. Returns True when the client-visible stream completed."""
        t0 = time.monotonic()
        exclude = set(exclude)
        if relay.finished:
            # The upstream died in the gap between its final
            # finish_reason-stamped frame and the [DONE] trailer: the
            # client already holds a complete completion — close it
            # cleanly instead of re-dispatching (every resume would 400
            # with nothing left to generate) and appending a spurious
            # truncation error to a finished stream.
            self.tracer.emit("failover", rid, outcome="already_complete",
                             tokens=len(relay.tokens))
            with contextlib.suppress(Exception):
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
            return True
        kind = ("chat.completion" if "chat" in request.path
                else "completion")
        self.tracer.emit("failover", rid, error=str(err)[:200] if err
                         else "", relayed_tokens=len(relay.tokens))
        attempts = 0
        while attempts < self.failover_attempts:
            target_url = self._ring_successor(key, exclude)
            if target_url is None:
                break
            attempts += 1
            exclude.add(target_url)
            target = next(r for r in self.replicas if r.url == target_url)
            headers = {REQUEST_ID_HEADER: rid}
            if self.qos_tiers:
                # A header-classed stream keeps its QoS class across the
                # failover hop (the resume handler can only re-derive the
                # user-pin/default rungs from the replayed body).
                _, qos_hdr = self._qos_resolve(request, obj)
                if qos_hdr is not None:
                    headers[QOS_TIER_HEADER] = qos_hdr
            nxt = self._ring_successor(key, exclude)
            if nxt is not None:
                # The resumed stream is itself survivable: name ITS
                # drain-push target so a second drain walks on.
                headers[MIGRATE_URL_HEADER] = nxt
            payload = {"body": obj, "kind": kind,
                       "relayed_token_ids": list(relay.tokens)}
            relay.reset_buffer()
            target.inflight += 1
            try:
                resume_cm = self._session.post(
                    f"{target_url}/internal/resume", json=payload,
                    headers=headers)
                upstream = await asyncio.wait_for(
                    resume_cm.__aenter__(), self.response_timeout_s)
                try:
                    if upstream.status != 200:
                        snippet = (await upstream.content.read(2048)
                                   ).decode("utf-8", errors="replace")
                        if (upstream.status == 400
                                and "nothing to resume" in snippet):
                            # The successor's engine confirms the replayed
                            # history already satisfies a stop condition
                            # (a finish the relay could not see): the
                            # stream is complete, not failed.
                            self.tracer.emit("failover", rid,
                                             replica=target_url,
                                             outcome="already_complete",
                                             tokens=len(relay.tokens))
                            with contextlib.suppress(Exception):
                                await resp.write(b"data: [DONE]\n\n")
                                await resp.write_eof()
                            return True
                        self.tracer.emit(
                            "failover", rid, replica=target_url,
                            attempt=attempts,
                            error=f"resume {upstream.status}: "
                                  f"{snippet[:120]}")
                        continue
                    mode = upstream.headers.get(RESUME_MODE_HEADER,
                                                "recompute")
                    self.failover_latency.observe(time.monotonic() - t0)
                    while True:
                        try:
                            chunk = await asyncio.wait_for(
                                upstream.content.readany(),
                                self.stall_timeout_s)
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError) as e2:
                            # The successor died too: walk on.
                            self._count_failure(target, e2,
                                                request_id=rid)
                            self.tracer.emit("failover", rid,
                                             replica=target_url,
                                             attempt=attempts,
                                             error=str(e2)[:200])
                            relay.reset_buffer()
                            break
                        if not chunk:
                            break
                        out = relay.feed(chunk)
                        try:
                            if out:
                                await resp.write(out)
                        except (ConnectionError, aiohttp.ClientError):
                            self.tracer.emit("abort", rid,
                                             reason="client_disconnect")
                            return True     # client gone; stop here
                    if relay.done:
                        outcome = ("import" if mode == "import"
                                   else "recompute")
                        self.failovers_total[outcome] = (
                            self.failovers_total.get(outcome, 0) + 1)
                        self.tracer.emit("failover", rid,
                                         replica=target_url,
                                         attempt=attempts, outcome=outcome,
                                         tokens=len(relay.tokens))
                        self.flight.dump("midstream_failover",
                                         request_id=rid, outcome=outcome,
                                         replica=target_url,
                                         attempts=attempts)
                        with contextlib.suppress(Exception):
                            await resp.write_eof()
                        return True
                finally:
                    with contextlib.suppress(Exception):
                        await resume_cm.__aexit__(None, None, None)
            except (aiohttp.ClientError, asyncio.TimeoutError) as e2:
                self._count_failure(target, e2, request_id=rid)
                self.tracer.emit("failover", rid, replica=target_url,
                                 attempt=attempts, error=str(e2)[:200])
                continue
            finally:
                target.inflight -= 1
        # Resume impossible: close the ladder LOUDLY — an explicit error
        # frame with the request id, then a clean stream end (a silent
        # truncation would read as a finished completion).
        self.failovers_total["failed"] = (
            self.failovers_total.get("failed", 0) + 1)
        self.tracer.emit("failover", rid, outcome="failed",
                         attempts=attempts, tokens=len(relay.tokens))
        self.flight.dump("midstream_failover", request_id=rid,
                         outcome="failed", attempts=attempts)
        logger.warning("mid-stream failover failed after %d attempt(s); "
                       "truncating the stream", attempts,
                       extra={"request_id": rid})
        err_body = {"error": {
            "message": ("stream truncated: the serving replica died "
                        "mid-stream and resume failed after "
                        f"{attempts} attempt(s)"),
            "type": "upstream_error", "code": 502, "request_id": rid}}
        with contextlib.suppress(Exception):
            await resp.write(b"data: " + json.dumps(err_body).encode()
                             + b"\n\n")
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        return False

    def _count_failure(self, replica: Replica, err: Exception,
                       request_id: str = "") -> None:
        replica.consecutive_failures += 1
        if self.peer_scores.record_timeout(replica.url):
            # Quarantine ENTRY (repeat offender): counted once per window
            # and black-boxed — the replica leaves the pick walk until
            # the window lapses and a healthy probe recovers it.
            logger.warning("replica %s quarantined for >= %.0fs "
                           "(repeated failures: %s)", replica.url,
                           self.peer_scores.quarantine_s, err,
                           extra=({"request_id": request_id}
                                  if request_id else None))
            self.flight.dump("peer_quarantine", peer=replica.url,
                             request_id=request_id, error=str(err)[:200])
        if replica.consecutive_failures >= self.fail_threshold:
            replica.healthy = False
            replica.benched_until = time.monotonic() + self.bench_cooldown_s
            logger.warning("replica %s marked unhealthy for >= %.0fs (%s)",
                           replica.url, self.bench_cooldown_s, err,
                           extra=({"request_id": request_id}
                                  if request_id else None))




def main(argv: Optional[list[str]] = None) -> None:
    """CLI: python -m kubernetes_gpu_cluster_tpu.serving.router
    --replicas http://pod-0:8000,http://pod-1:8000 --port 8080"""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--replicas", required=True,
                   help="comma-separated replica base URLs (the client-"
                   "facing pool: role 'both', or 'decode' when "
                   "--prefill-replicas names a prefill pool)")
    p.add_argument("--prefill-replicas", default=None,
                   help="disaggregated prefill/decode: comma-separated "
                   "base URLs of the PREFILL pool (replicas started with "
                   "--role prefill). Completions are proxied to the main "
                   "pool with an x-kgct-prefill-url header naming the "
                   "prefix-affine prefill replica to pull KV from; absent "
                   "or unhealthy prefill replicas degrade to colocated "
                   "local prefill")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--routing-policy", default="least-inflight",
                   choices=["least-inflight", "prefix-affinity"],
                   help="least-inflight: fewest outstanding requests wins "
                   "(the pre-affinity behavior, default). prefix-affinity: "
                   "bounded-load consistent hashing on the prompt prefix / "
                   "session_id so repeat traffic lands on the replica whose "
                   "prefix cache is warm")
    p.add_argument("--affinity-prefix-len", type=int, default=32,
                   help="prefix-affinity: tokens of prompt prefix hashed "
                   "into the routing key (token-array prompts use this many "
                   "ids; text prompts use 4x this many UTF-8 bytes)")
    p.add_argument("--balance-factor", type=float, default=1.5,
                   help="prefix-affinity: CHWBL load bound — a ring owner "
                   "above ceil(factor * mean inflight) spills the request "
                   "to its ring successor (1.0 = strict fair share; larger "
                   "= stickier)")
    p.add_argument("--qos-tiers", default=None,
                   help="multi-tenant QoS tier config (same JSON as the "
                   "engine's --qos-tiers, or 'default'): the router "
                   "resolves each request's tier (header > session_id/"
                   "user pin > default), propagates it upstream in "
                   "x-kgct-qos-tier, and exposes per-tier inflight on "
                   "/health and /metrics. Unset = tier-less routing, "
                   "byte-identical to before")
    p.add_argument("--qos-default-tier", default=None,
                   help="tier applied to requests that name none; "
                   "default: the first configured tier")
    args = p.parse_args(argv)
    qos_tiers: tuple = ()
    if args.qos_tiers:
        # Lazy import: a tier-less router never loads the config package.
        from ..config.qos import parse_qos_tiers
        try:
            qos_tiers = parse_qos_tiers(args.qos_tiers)
        except ValueError as e:
            p.error(str(e))
        if (args.qos_default_tier is not None
                and args.qos_default_tier not in {t.name
                                                  for t in qos_tiers}):
            p.error(f"--qos-default-tier {args.qos_default_tier!r} is not "
                    "a configured tier")
    elif args.qos_default_tier is not None:
        p.error("--qos-default-tier requires --qos-tiers")
    router = Router(args.replicas.split(","),
                    routing_policy=args.routing_policy,
                    affinity_prefix_len=args.affinity_prefix_len,
                    balance_factor=args.balance_factor,
                    prefill_urls=(args.prefill_replicas.split(",")
                                  if args.prefill_replicas else None),
                    qos_tiers=qos_tiers,
                    qos_default_tier=args.qos_default_tier)
    web.run_app(router.build_app(), host=args.host, port=args.port)


if __name__ == "__main__":
    main()
