"""Request router: one front door over N data-parallel engine replicas.

The reference exposed its replicas behind ``vllm-router-service`` and
operators port-forwarded to it (``old_README.md:1174-1176, 1472-1476``);
replicas were plain Deployment pods spread by anti-affinity
(``values-01-minimal-example2.yaml:10, 23-49``). This router is the native
equivalent: an aiohttp reverse proxy that

- tracks replica health (probed immediately at startup, then periodic GET
  /health; unhealthy replicas leave the rotation and return on recovery —
  the k8s-native restart/rollout story of SURVEY §5.3 at the traffic layer),
- balances by least-outstanding-requests (better than round-robin under
  continuous batching: a replica stuck on long generations accumulates
  in-flight count and sheds new work),
- streams responses through unbuffered (SSE passthrough),
- hardens every upstream call: per-attempt connect timeouts, a per-read
  stall timeout that circuit-breaks replicas whose in-flight streams hang,
  and bounded exponential-backoff retry of connect-phase failures (the only
  phase where nothing reached the upstream, so re-sending is safe).

Chaos sites (resilience.faults): ``router_connect`` simulates a connect
failure on the picked replica, ``replica_hang`` a mid-stream read timeout.

In-cluster, replica discovery is the headless-Service DNS name; static URLs
work for local/dev. Deployment manifests are rendered by
kubernetes_gpu_cluster_tpu.deploy (router Deployment + kgct-router-service).
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import time
from typing import Optional

import aiohttp
from aiohttp import web

from ..resilience.faults import inject as _inject_fault
from ..utils import get_logger
# The engine's shed/drain responses use the same envelope (serving.errors):
# a router-level 503 is handled by the identical client code path.
from .errors import overloaded_error as _proxy_error

logger = get_logger("serving.router")

# Connect-PHASE failures: nothing reached the upstream, so failover/retry is
# provably safe. ConnectionTimeoutError (sock_connect expired — the
# blackholed-node case, no RST ever comes back) is distinct from the
# sock_read ServerTimeoutError and joins the refused/unreachable class;
# older aiohttp without the split falls back to connector errors only.
CONNECT_PHASE_ERRORS: tuple = (aiohttp.ClientConnectorError,
                               ConnectionRefusedError)
if hasattr(aiohttp, "ConnectionTimeoutError"):
    CONNECT_PHASE_ERRORS += (aiohttp.ConnectionTimeoutError,)

HOP_HEADERS = {"transfer-encoding", "content-length", "connection",
               "keep-alive", "host"}


class Replica:
    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = True
        self.inflight = 0
        self.consecutive_failures = 0
        # Traffic-failure bench expiry: a replica broken by proxy failures
        # (connect/stall) may still answer /health 200 — its wedge detector
        # (engine watchdog) is much slower than the router's. Probe success
        # must not restore it before this cooldown, or traffic bounces
        # straight back onto the wedged replica.
        self.benched_until = 0.0


class Router:
    def __init__(self, replica_urls: list[str],
                 health_interval_s: float = 5.0,
                 fail_threshold: int = 2,
                 connect_timeout_s: float = 5.0,
                 stall_timeout_s: float = 60.0,
                 response_timeout_s: float = 300.0,
                 metrics_timeout_s: float = 2.0,
                 connect_retries: int = 2,
                 retry_backoff_s: float = 0.25,
                 bench_cooldown_s: float = 30.0):
        self.replicas = [Replica(u) for u in replica_urls]
        self.health_interval_s = health_interval_s
        self.fail_threshold = fail_threshold
        self.connect_timeout_s = connect_timeout_s
        # Max seconds between CHUNKS once a response is streaming before the
        # replica is declared stalled (generous: an overloaded engine can
        # pause seconds between tokens; a wedged one goes silent forever).
        self.stall_timeout_s = stall_timeout_s
        # Max seconds to FIRST response bytes (headers). Deliberately much
        # larger than stall_timeout_s: a non-streaming completion sends
        # nothing until the whole generation finishes, and a slow-but-
        # correct generation must not 502 or count toward fail_threshold.
        self.response_timeout_s = response_timeout_s
        self.metrics_timeout_s = metrics_timeout_s
        # Connect-phase failures retry the whole replica set up to this many
        # extra rounds with exponential backoff — rides out the blip where
        # every replica is briefly restarting.
        self.connect_retries = connect_retries
        self.retry_backoff_s = retry_backoff_s
        self.bench_cooldown_s = bench_cooldown_s
        self.retries_total = 0
        self.scrape_errors_total = 0
        self._rr = itertools.count()
        self._session: Optional[aiohttp.ClientSession] = None
        self._health_task: Optional[asyncio.Task] = None

    # -- app wiring ----------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/health", self.health)
        app.router.add_get("/v1/models", self.proxy)
        app.router.add_post("/v1/completions", self.proxy)
        app.router.add_post("/v1/chat/completions", self.proxy)
        app.router.add_get("/metrics", self.metrics)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, app: web.Application) -> None:
        # No session-wide sock_read: phase-specific deadlines are applied at
        # the call sites (response_timeout_s for headers, stall_timeout_s
        # between stream chunks) — a blanket read timeout would 502
        # legitimately slow non-streaming generations.
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=None, sock_connect=self.connect_timeout_s))
        # Cold-start probe: without it, a replica that is down RIGHT NOW
        # still receives traffic for up to fail_threshold x interval before
        # the periodic loop notices. One failed startup probe removes it
        # immediately; the loop restores it on recovery.
        await asyncio.gather(
            *(self._check(r, startup=True) for r in self.replicas),
            return_exceptions=True)
        self._health_task = asyncio.create_task(self._health_loop())

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._health_task:
            self._health_task.cancel()
        if self._session:
            await self._session.close()

    # -- health --------------------------------------------------------------

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.health_interval_s)
            await asyncio.gather(*(self._check(r) for r in self.replicas),
                                 return_exceptions=True)

    async def _check(self, replica: Replica, startup: bool = False) -> None:
        try:
            async with self._session.get(
                    f"{replica.url}/health",
                    timeout=aiohttp.ClientTimeout(total=5)) as resp:
                ok = resp.status == 200
        except Exception:
            ok = False
        if ok:
            if time.monotonic() < replica.benched_until:
                # Benched by TRAFFIC failures: a 200 probe proves only that
                # /health answers, not that proxied streams stopped
                # stalling (the engine's own wedge detector is slower than
                # ours) — sit out the cooldown before trusting it again.
                return
            replica.consecutive_failures = 0
            if not replica.healthy:
                logger.info("replica %s back in rotation", replica.url)
            replica.healthy = True
        else:
            replica.consecutive_failures += 1
            # At startup a single failure is disqualifying (no traffic
            # history argues for the replica); in steady state the threshold
            # rides out transient blips.
            if (replica.healthy
                    and (startup or replica.consecutive_failures
                         >= self.fail_threshold)):
                logger.warning("replica %s marked unhealthy%s", replica.url,
                               " (startup probe)" if startup else "")
                replica.healthy = False

    async def health(self, request: web.Request) -> web.Response:
        healthy = [r.url for r in self.replicas if r.healthy]
        status = 200 if healthy else 503
        return web.json_response(
            {"status": "ok" if healthy else "no healthy replicas",
             "replicas": {r.url: {"healthy": r.healthy,
                                  "inflight": r.inflight}
                          for r in self.replicas}},
            status=status)

    async def metrics(self, request: web.Request) -> web.Response:
        lines = ["# TYPE kgct_router_replica_healthy gauge"]
        lines += [f'kgct_router_replica_healthy{{replica="{r.url}"}} '
                  f"{int(r.healthy)}" for r in self.replicas]
        lines.append("# TYPE kgct_router_replica_inflight gauge")
        lines += [f'kgct_router_replica_inflight{{replica="{r.url}"}} '
                  f"{r.inflight}" for r in self.replicas]
        lines += ["# TYPE kgct_router_retries_total counter",
                  f"kgct_router_retries_total {self.retries_total}"]
        # Aggregate each healthy replica's engine metrics behind the single
        # front door (one scrape target for the whole DP group), labelled by
        # replica so series do not collide. Each per-replica fetch is bounded
        # (metrics_timeout_s): one stalled replica must not hang the whole
        # scrape — stragglers are skipped and counted instead.
        fetched = await asyncio.gather(
            *(self._fetch_metrics(r) for r in self.replicas if r.healthy),
            return_exceptions=True)
        self.scrape_errors_total += sum(
            1 for res in fetched if isinstance(res, BaseException))
        lines += ["# TYPE kgct_router_metrics_scrape_errors_total counter",
                  "kgct_router_metrics_scrape_errors_total "
                  f"{self.scrape_errors_total}"]
        # Regroup by metric family: the text exposition format requires ONE
        # TYPE line per family with ALL its samples contiguous — appending
        # replicas' expositions sequentially interleaves families and strict
        # parsers (promtool/OpenMetrics) reject the whole scrape.
        families: dict[str, dict] = {}
        for res in fetched:
            if isinstance(res, BaseException):
                continue
            for family, is_type, line in res:
                fam = families.setdefault(family, {"type": None, "samples": []})
                if is_type:
                    if fam["type"] is None:
                        fam["type"] = line
                else:
                    fam["samples"].append(line)
        for fam in families.values():
            if fam["type"] is not None:
                lines.append(fam["type"])
            lines.extend(fam["samples"])
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _fetch_metrics(self, replica: Replica):
        """Returns (family, is_type, line) triples with samples relabelled by
        replica. Family attribution follows the exposition's own ordering —
        a TYPE line opens a family and subsequent samples whose base name is
        the family (or family + ``_suffix``, the summary/histogram
        ``_sum``/``_count``/``_bucket`` children) belong to it."""
        async with self._session.get(
                f"{replica.url}/metrics",
                timeout=aiohttp.ClientTimeout(total=self.metrics_timeout_s)
                ) as resp:
            text = await resp.text()
        label = f'replica="{replica.url}"'
        out = []
        current = None
        for line in text.splitlines():
            if not line or line.startswith("#"):
                if line.startswith("# TYPE"):
                    parts = line.split()
                    current = parts[2] if len(parts) > 2 else line
                    out.append((current, True, line))
                continue
            name, _, rest = line.partition(" ")
            base = name.partition("{")[0]
            family = (current if current and
                      (base == current or base.startswith(current + "_"))
                      else base)
            if "{" in name:
                labels = name.partition("{")[2]
                out.append((family, False, f"{base}{{{label},{labels} {rest}"))
            else:
                out.append((family, False, f"{base}{{{label}}} {rest}"))
        return out

    # -- proxying ------------------------------------------------------------

    def _pick(self, exclude: Optional[set] = None,
              include_unhealthy: bool = False) -> Optional[Replica]:
        healthy = [r for r in self.replicas
                   if (r.healthy or include_unhealthy)
                   and (not exclude or r.url not in exclude)]
        if not healthy:
            return None
        least = min(r.inflight for r in healthy)
        tied = [r for r in healthy if r.inflight == least]
        return tied[next(self._rr) % len(tied)]

    async def proxy(self, request: web.Request) -> web.StreamResponse:
        """Reverse-proxy with failover.

        Only CONNECT-phase failures (replica down/unreachable) fail over to
        the next healthy replica — a request the upstream already received
        may be mid-generation there, and re-sending it would silently double
        device work under exactly the overload that causes resets. When
        every healthy replica fails the connect phase, the whole set is
        retried up to ``connect_retries`` more rounds with exponential
        backoff. Upstream errors after the body was delivered return 502;
        after streaming to the client started, the stream is terminated
        (truncation is the signal) and the stall/death circuit-breaks the
        replica. Client-side disconnects never count against the replica."""
        body = await request.read()
        tried: set[str] = set()
        last_err: Optional[Exception] = None
        connect_failed = False
        rounds = 0
        while True:
            # Retry rounds (rounds > 0) ignore the healthy flag: the connect
            # failures that triggered the retry are exactly what benched the
            # replicas (fail_threshold), and a retry restricted to healthy
            # ones would find nothing and give up — defeating its purpose of
            # riding out a restart blip. Nothing reached any upstream, so a
            # desperation probe of benched replicas is safe.
            replica = self._pick(exclude=tried,
                                 include_unhealthy=rounds > 0)
            if replica is None:
                # Every candidate this round failed at connect: nothing was
                # sent anywhere, so a bounded backed-off re-probe of the
                # full set is safe (replicas restart in seconds under k8s).
                if connect_failed and rounds < self.connect_retries and tried:
                    await asyncio.sleep(
                        self.retry_backoff_s * (2 ** rounds))
                    rounds += 1
                    tried.clear()
                    connect_failed = False
                    continue
                break
            tried.add(replica.url)
            replica.inflight += 1
            try:
                try:
                    if _inject_fault("router_connect"):
                        raise ConnectionRefusedError(
                            "KGCT_FAULT router_connect")
                    upstream_cm = self._session.request(
                        request.method, f"{replica.url}{request.path_qs}",
                        data=body if body else None,
                        headers={k: v for k, v in request.headers.items()
                                 if k.lower() not in HOP_HEADERS})
                    # Headers deadline: a replica that accepted the request
                    # and then never responds at all is wedged — but the
                    # bound is the generous response_timeout_s, because a
                    # non-streaming completion legitimately sends nothing
                    # until the whole generation finishes.
                    upstream = await asyncio.wait_for(
                        upstream_cm.__aenter__(), self.response_timeout_s)
                except CONNECT_PHASE_ERRORS as e:
                    # TCP connect failed or timed out: nothing reached the
                    # upstream — safe to fail over.
                    last_err = e
                    connect_failed = True
                    self.retries_total += 1
                    self._count_failure(replica, e)
                    continue
                except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                    # Request sent (at least partially) but no response —
                    # including a replica that accepted the body then went
                    # silent past stall_timeout_s: the upstream may already
                    # be processing it — do NOT re-send.
                    last_err = e
                    self._count_failure(replica, e)
                    break
                try:
                    resp = web.StreamResponse(status=upstream.status)
                    for k, v in upstream.headers.items():
                        if k.lower() not in HOP_HEADERS:
                            resp.headers[k] = v
                    await resp.prepare(request)
                    while True:
                        try:
                            if _inject_fault("replica_hang"):
                                raise asyncio.TimeoutError(
                                    "KGCT_FAULT replica_hang")
                            # Per-chunk stall deadline: once streaming, a
                            # healthy engine emits tokens continuously —
                            # stall_timeout_s of silence means the replica
                            # hung mid-generation.
                            chunk = await asyncio.wait_for(
                                upstream.content.readany(),
                                self.stall_timeout_s)
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError) as e:
                            # Upstream died or stalled mid-stream (no bytes
                            # for stall_timeout_s): circuit-break the
                            # replica; the client stream is already
                            # committed — terminate it (truncation is the
                            # signal).
                            self._count_failure(replica, e)
                            with contextlib.suppress(Exception):
                                await resp.write_eof()
                            return resp
                        if not chunk:
                            break
                        try:
                            await resp.write(chunk)
                        except (ConnectionError, aiohttp.ClientError):
                            # CLIENT went away — not the replica's fault; no
                            # failure accounting.
                            return resp
                    await resp.write_eof()
                    return resp
                finally:
                    await upstream_cm.__aexit__(None, None, None)
            finally:
                replica.inflight -= 1
        if last_err is not None:
            return _proxy_error(502, f"upstream error: {last_err}",
                                retry_after_s=1)
        return _proxy_error(
            503, "no healthy replicas; retry shortly",
            retry_after_s=max(int(self.health_interval_s), 1))

    def _count_failure(self, replica: Replica, err: Exception) -> None:
        replica.consecutive_failures += 1
        if replica.consecutive_failures >= self.fail_threshold:
            replica.healthy = False
            replica.benched_until = time.monotonic() + self.bench_cooldown_s
            logger.warning("replica %s marked unhealthy for >= %.0fs (%s)",
                           replica.url, self.bench_cooldown_s, err)




def main(argv: Optional[list[str]] = None) -> None:
    """CLI: python -m kubernetes_gpu_cluster_tpu.serving.router
    --replicas http://pod-0:8000,http://pod-1:8000 --port 8080"""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--replicas", required=True,
                   help="comma-separated replica base URLs")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    args = p.parse_args(argv)
    router = Router(args.replicas.split(","))
    web.run_app(router.build_app(), host=args.host, port=args.port)


if __name__ == "__main__":
    main()
