"""Fleet-wide KV reuse: policy + plumbing for the global prefix cache.

N per-replica prefix caches become one fleet cache (Mooncake-style
KVCache-centric pooling): when the affinity router's pick cannot land on
the ring owner (over-bound or out of rotation), the chosen replica PULLS
the owner's cached prefix pages over the existing handoff substrate
(``POST /internal/fetch_prefix`` + the streamed prefix codec in
serving/handoff.py) instead of recomputing them, and eviction gains a
remote-spill rung (cold prefixes move to a peer's host tier before being
dropped). This module owns the two engine-free halves the api_server
composes:

- the ANTI-THRASH pull policy: a roofline price of pull vs recompute.
  Remote KV pull beats recompute exactly when transfer bandwidth outruns
  prefill FLOPs (the DistServe/Mooncake observation); per token the two
  sides are ``kv_bytes_per_token / link_bandwidth`` against
  ``prefill_flops_per_token / achievable_flops`` — never fetch what is
  cheaper to re-prefill. The FLOPs model mirrors bench.py's prefill
  roofline matmul term (the quadratic attention term is EXCLUDED: that
  underestimates recompute cost, which biases the gate toward skipping —
  the safe anti-thrash direction);
- the BOUNDED spill queue: eviction runs on the engine worker thread and
  must never block on a socket, so the remote-spill hook only enqueues
  (drop-oldest beyond the cap) and an async serving task drains the queue
  toward allowlisted peers (``--peer-pool``);
- the PEER SCOREBOARD: per-peer reputation over the KV wire plane.
  Corruptions and timeouts decay a health score; a peer that sinks below
  the quarantine threshold is excluded from pulls/spills/migration
  targets for a backoff window, after which the NEXT attempt is the probe
  (success restores, another failure re-quarantines). The router keeps
  its own scoreboard over the same class for the ``_pick`` walk.

Everything here is engine-free and jax-free so tests pin the policy
arithmetic and the queue bounds without building an engine.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Optional

# Link bandwidth assumed by the pull gate when the operator does not
# override it (KGCT_FLEET_BW_GBPS): a conservative intra-cluster figure —
# pod-to-pod TCP inside one rack comfortably sustains this, and
# underestimating bandwidth only makes the gate MORE reluctant to pull.
DEFAULT_LINK_GBPS = 8.0

# Achievable prefill FLOP/s assumed per backend when the operator does not
# override it (KGCT_FLEET_FLOPS). TPU: a deliberately generous fraction of
# a v5e's bf16 peak so the gate stays skeptical of pulls on hardware where
# recompute is genuinely fast; CPU: the measured order of magnitude of the
# XLA CPU prefill path on one core (where recompute is expensive and
# pulling almost always wins).
DEFAULT_FLOPS = {"tpu": 80e12, "cpu": 5e9}

# Bounded spill queue: pages parked for the async peer push. Beyond the
# cap the OLDEST entry drops (newer evictions are warmer) — a burst of
# eviction pressure must never balloon host memory with in-flight spills.
SPILL_QUEUE_CAP = 32

# Peer-reputation defaults. A single corruption quarantines immediately
# (a checksum mismatch is never noise — either the wire or the peer is
# lying about bytes); timeouts take a few in a row (transient congestion
# is normal). Scores recover multiplicatively on success so one good
# probe after the window restores full standing quickly but not
# instantly.
PEER_SCORE_START = 1.0
PEER_CORRUPT_COST = 1.0
PEER_TIMEOUT_COST = 0.3
PEER_RECOVERY_GAIN = 0.5
PEER_QUARANTINE_THRESHOLD = 0.25
PEER_QUARANTINE_S = 30.0


@dataclasses.dataclass(frozen=True)
class PullPolicy:
    """The anti-thrash gate: pull a prefix only when the roofline prices
    the transfer below the recompute. All three knobs resolve once at
    server construction; the decision itself is a pure function so tests
    pin both directions with injected constants."""

    link_bytes_per_s: float
    flops_per_s: float
    kv_bytes_per_token: float
    flops_per_token: float
    min_tokens: int = 1

    def pull_beats_recompute(self, n_tokens: int) -> bool:
        """Price ``n_tokens`` of prefix: transfer wall vs re-prefill wall.
        Below ``min_tokens`` (sub-page matches) nothing is ever pulled."""
        if n_tokens < self.min_tokens:
            return False
        transfer_s = n_tokens * self.kv_bytes_per_token / self.link_bytes_per_s
        recompute_s = n_tokens * self.flops_per_token / self.flops_per_s
        return transfer_s < recompute_s

    def describe(self) -> dict:
        """One-line policy readout for logs/traces."""
        return {
            "link_gbps": round(self.link_bytes_per_s / 1e9, 3),
            "flops_per_s": self.flops_per_s,
            "kv_bytes_per_token": round(self.kv_bytes_per_token, 1),
            "flops_per_token": round(self.flops_per_token, 1),
            "min_tokens": self.min_tokens,
        }


def prefill_flops_per_token(model_cfg) -> float:
    """Matmul FLOPs to prefill one token (2 FLOPs/MAC over the attention
    projections + routed MLP experts, every layer) — the same accounting
    as bench.py's prefill roofline, minus the T^2 attention term (see
    module docstring for why excluding it is the safe direction)."""
    h, inter = model_cfg.hidden_size, model_cfg.intermediate_size
    nh, nkv, hd = (model_cfg.num_heads, model_cfg.num_kv_heads,
                   model_cfg.head_dim)
    attn_p = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
    mlp_unit = 3 * h * inter
    active_exp = (model_cfg.num_experts_per_tok
                  if getattr(model_cfg, "is_moe", False) else 1)
    return float(2 * model_cfg.num_layers * (attn_p + active_exp * mlp_unit))


def kv_bytes_per_token(model_cfg, itemsize: int) -> float:
    """Wire bytes per token of cached prefix: K and V across every layer
    at the pool dtype."""
    return float(2 * model_cfg.num_layers * model_cfg.num_kv_heads
                 * model_cfg.head_dim * itemsize)


def build_pull_policy(model_cfg, page_size: int, itemsize: int,
                      backend: str) -> PullPolicy:
    """Resolve the gate's constants once: env overrides
    (``KGCT_FLEET_BW_GBPS`` / ``KGCT_FLEET_FLOPS``) beat the backend
    defaults; ``min_tokens`` is one page — the cache's own reuse
    granularity."""
    gbps = float(os.environ.get("KGCT_FLEET_BW_GBPS", DEFAULT_LINK_GBPS))
    flops = float(os.environ.get(
        "KGCT_FLEET_FLOPS", DEFAULT_FLOPS.get(backend, DEFAULT_FLOPS["cpu"])))
    return PullPolicy(
        link_bytes_per_s=gbps * 1e9,
        flops_per_s=flops,
        kv_bytes_per_token=kv_bytes_per_token(model_cfg, itemsize),
        flops_per_token=prefill_flops_per_token(model_cfg),
        min_tokens=page_size)


class SpillQueue:
    """Bounded drop-oldest queue between the engine worker's eviction hook
    (producer, must never block) and the serving-side async peer push
    (consumer). Thread-safe by GIL-atomicity of deque append/popleft —
    single producer, single consumer, no locks on the eviction path."""

    def __init__(self, cap: int = SPILL_QUEUE_CAP):
        self._q: deque = deque(maxlen=cap)
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._q)

    def offer(self, digest_hex: str, k_np, v_np) -> bool:
        """Enqueue one evicted page; True when nothing was displaced.
        A full queue drops its OLDEST entry (deque maxlen semantics) —
        counted, so the spill metrics attribute the loss."""
        displaced = len(self._q) == self._q.maxlen
        if displaced:
            self.dropped += 1
        self._q.append((digest_hex, k_np, v_np))
        return not displaced

    def pop(self) -> Optional[tuple]:
        try:
            return self._q.popleft()
        except IndexError:
            return None


class PeerScoreboard:
    """Per-peer reputation over the KV wire plane (pulls, spills,
    migration pushes — and, with its own instance, the router's proxy
    walk). Single-threaded by construction (every caller runs on one
    event loop), clock-injectable so tests pin the window arithmetic.

    Lifecycle of a misbehaving peer: failures decay its score
    (corruption >> timeout); crossing ``threshold`` quarantines it for
    ``quarantine_s`` — :meth:`quarantined` excludes it from every target
    walk. Once the window lapses the peer is AUTOMATICALLY a probe
    candidate again (still at its decayed score): one success recovers
    the score toward healthy, one more failure re-quarantines for a
    fresh window. No unbounded state: one entry per allowlisted peer."""

    def __init__(self, threshold: float = PEER_QUARANTINE_THRESHOLD,
                 corrupt_cost: float = PEER_CORRUPT_COST,
                 timeout_cost: float = PEER_TIMEOUT_COST,
                 recovery: float = PEER_RECOVERY_GAIN,
                 quarantine_s: float = PEER_QUARANTINE_S,
                 clock=None):
        self.threshold = threshold
        self.corrupt_cost = corrupt_cost
        self.timeout_cost = timeout_cost
        self.recovery = recovery
        self.quarantine_s = quarantine_s
        self._clock = clock if clock is not None else time.monotonic
        self._score: dict[str, float] = {}
        self._until: dict[str, float] = {}
        # Total quarantine ENTRIES per peer (the metric counter): only
        # the below-threshold transition increments, not every excluded
        # attempt during a window.
        self.quarantines: dict[str, int] = {}
        # Ever-quarantined peers whose recovery has not been observed
        # yet: lets callers trace the probe-recovery transition.
        self._in_quarantine: set = set()

    def score(self, peer: str) -> float:
        return self._score.get(peer, PEER_SCORE_START)

    def quarantined(self, peer: str) -> bool:
        """True while ``peer`` sits inside an active backoff window —
        excluded from pulls/spills/migration targets. The first attempt
        AFTER the window is the probe: this returns False then, and the
        attempt's outcome decides recovery vs re-quarantine."""
        return self._clock() < self._until.get(peer, 0.0)

    def retry_after_s(self, peer: str) -> float:
        """Seconds left in the peer's backoff window (0 when none) — the
        Retry-After a quarantine-derived 503 carries."""
        return max(0.0, self._until.get(peer, 0.0) - self._clock())

    def record_ok(self, peer: str) -> None:
        """A successful exchange (probe included): recover the score
        toward healthy and clear any lapsed window."""
        s = min(PEER_SCORE_START,
                self.score(peer) + self.recovery)
        self._score[peer] = s
        if peer in self._in_quarantine and s >= self.threshold:
            self._in_quarantine.discard(peer)
            self._until.pop(peer, None)

    def record_timeout(self, peer: str) -> bool:
        """One timeout/transport failure; True when this ENTERED
        quarantine (the caller's cue to count/dump the transition)."""
        return self._decay(peer, self.timeout_cost)

    def record_corruption(self, peer: str) -> bool:
        """One checksum/protocol detection; True when this ENTERED
        quarantine."""
        return self._decay(peer, self.corrupt_cost)

    def _decay(self, peer: str, cost: float) -> bool:
        """Apply one failure; True when this ENTERED quarantine (a
        failure landing inside an already-active window extends it but
        does not re-count — in-flight exchanges against a peer that just
        crossed must not inflate the entry counter)."""
        s = max(0.0, self.score(peer) - cost)
        self._score[peer] = s
        if s < self.threshold:
            now = self._clock()
            entered = now >= self._until.get(peer, 0.0)
            if entered:
                self.quarantines[peer] = self.quarantines.get(peer, 0) + 1
            self._until[peer] = now + self.quarantine_s
            self._in_quarantine.add(peer)
            return entered
        return False
