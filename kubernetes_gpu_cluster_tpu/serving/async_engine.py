"""AsyncLLMEngine: asyncio front door over the blocking LLMEngine.

The engine's step() blocks on device sync, so it runs on a dedicated worker
thread; request submission and output streaming cross the thread boundary
through a thread-safe inbox and ``loop.call_soon_threadsafe`` fan-out into
per-request asyncio queues. This is the piece that turns the batch engine
into the always-on serving process behind the OpenAI API (the role vLLM's
AsyncLLMEngine played inside the images the reference deployed,
``old_README.md:1078-1176``).

The worker thread idles on a condition variable when there is no work — an
idle replica burns no CPU and wakes in O(µs) on the first request.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import threading
from typing import AsyncIterator, Optional

from ..analysis.sanitize import build_interleave_sanitizer
from ..config import EngineConfig
from ..engine import LLMEngine, RequestOutput, SamplingParams
from ..utils import get_logger

logger = get_logger("serving.async_engine")


@dataclasses.dataclass
class StreamChunk:
    """One step's worth of progress for a request."""
    request_id: str
    new_token_ids: list[int]
    output_token_ids: list[int]
    finished: bool
    finish_reason: Optional[str]
    new_logprobs: list[float] = dataclasses.field(default_factory=list)
    new_top_logprobs: list = dataclasses.field(default_factory=list)


class AsyncLLMEngine:
    def __init__(self, config: EngineConfig, params=None,
                 eos_token_id: Optional[int] = None, mesh=None,
                 leader=None, draft_params=None):
        """``leader``: serving.multihost.DirectiveLeader when this process
        is rank 0 of a multi-process mesh — every worker-loop iteration's
        (adds, aborts) are broadcast to follower ranks BEFORE the local
        apply+step so all engines schedule in SPMD lockstep.
        ``draft_params``: pre-loaded draft-model weights
        (--spec-draft-weights); None random-inits when spec_draft_model is
        configured."""
        self.engine = LLMEngine(config, params=params,
                                eos_token_id=eos_token_id, mesh=mesh,
                                draft_params=draft_params)
        self.leader = leader
        # resilience.StepWatchdog, set by APIServer: armed around each
        # step() so a hung device dispatch flips /health instead of parking
        # requests forever behind a 200-ok server.
        self.watchdog = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: dict[str, asyncio.Queue] = {}
        # Ids reserved via reserve_request_id whose generate() has not
        # started yet — lets release_reservation() free a slot the handler
        # abandoned (client died between reserve and first iteration)
        # without ever touching a live generator's queue.
        self._reserved: set = set()
        self._inbox: list = []            # (request_id, token_ids, params)
        self._aborts: list[str] = []
        # Disaggregated prefill/decode side-channels, keyed by request id so
        # the inbox tuples keep the exact shape the multihost directive
        # broadcast serializes: _handoffs holds a decoded KV-handoff state
        # an inbox entry should IMPORT instead of prefilling; _holds marks
        # entries whose finished KV the export seam will collect. Both are
        # leader-gated at generate() — handoff does not compose with SPMD
        # lockstep (followers would never see the import).
        self._handoffs: dict[str, dict] = {}
        self._holds: set = set()
        # Mid-stream failover: already-relayed output token ids to replay
        # as forced context when an entry is admitted WITHOUT (or after a
        # failed) KV import — the recompute rung of the resume ladder.
        self._resumes: dict[str, list] = {}
        # Backdated arrival stamps (time.monotonic) for requests whose
        # handoff pull FAILED before admission: the burned pull wait is
        # client-observed TTFT and must reach the histogram/SLO window.
        self._arrival_t0s: dict[str, float] = {}
        # Serving-layer hook: an ENGINE-side import failure (no batch seat,
        # no pages, state mismatch) degrades to local recompute after the
        # pull was already accounted — without this the operator's fallback
        # counter reads 100% successful imports on a replica that recomputes
        # everything. Set by APIServer; called on the worker thread.
        self.on_import_fallback = None
        # Worker-thread operations (the export seam): (fn(engine), future)
        # pairs executed between steps, where every engine/scheduler/device
        # touch is single-threaded by construction.
        self._ops: list = []
        # KGCT_SANITIZE_INTERLEAVE: deterministic seeded yields at the
        # loop/worker seam crossings (None when off — every hook is one
        # `is None` test, byte-identical to the sanitizer being absent).
        self._interleave = build_interleave_sanitizer()
        self._cv = threading.Condition()
        self._shutdown = False
        self._counter = itertools.count()
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="kgct-engine-step-loop")

    # -- lifecycle -----------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        # get_running_loop, not get_event_loop: the fan-out posts chunks
        # via call_soon_threadsafe, and a loop silently CREATED here (off
        # the server's thread, never run) would swallow them forever —
        # kgct-lint KGCT006 pins this.
        self._loop = loop if loop is not None else asyncio.get_running_loop()
        self._thread.start()

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify()
        self._thread.join(timeout=30)
        if self.leader is not None:
            if self._thread.is_alive():
                # A wedged worker may still write the directive sockets;
                # closing now would interleave frames and corrupt the
                # follower's NDJSON stream. Leave the sockets to the OS.
                logger.warning("worker thread still alive after join "
                               "timeout; skipping leader close")
            else:
                self.leader.close()

    # -- request API ---------------------------------------------------------

    def next_request_id(self, prefix: str = "cmpl") -> str:
        return f"{prefix}-{next(self._counter)}"

    def reserve_request_id(self, request_id: str) -> bool:
        """Atomically claim ``request_id``'s output-queue slot (False if a
        live request already holds it). Synchronous on the event-loop
        thread — no await between check and claim — so the API layer calls
        this immediately before ``generate()`` and a concurrent duplicate
        of a client-supplied correlation id can never cross streams (an
        async-generator-side check would only run at first iteration,
        AFTER the caller's awaits — the TOCTOU this closes). Callers must
        pair it with :meth:`release_reservation` on every handler exit
        path, or an abandoned reservation would mark the id in-flight
        forever."""
        if request_id in self._queues:
            return False
        self._queues[request_id] = asyncio.Queue()
        self._reserved.add(request_id)
        return True

    def release_reservation(self, request_id: str) -> bool:
        """Free a reservation whose ``generate()`` never STARTED (the
        handler died between reserve and the generator's first iteration —
        e.g. ``resp.prepare`` raising on client disconnect). A no-op once
        the generator consumed the reservation: its own finally owns the
        queue's lifetime from then on.

        Returns True when a reservation WAS released — the engine never saw
        the request, so the caller must NOT enqueue an abort for it: a
        stale abort of a reused client-supplied id would terminate (or
        orphan) a LATER request that legitimately claims the same id."""
        if request_id in self._reserved:
            self._reserved.discard(request_id)
            self._queues.pop(request_id, None)
            return True
        return False

    async def generate(self, request_id: str, prompt_token_ids: list[int],
                       params: SamplingParams, handoff: dict = None,
                       hold_kv: bool = False,
                       arrival_t0: Optional[float] = None,
                       resume_outputs: Optional[list] = None
                       ) -> AsyncIterator[StreamChunk]:
        """Submit a request and yield StreamChunks until finished.

        Id contract: serving callers reserve the id first (see
        reserve_request_id, looped until owned); a DIRECT caller must use
        an id it knows to be unique — calling with an id that has a
        pending reservation would consume the reserver's slot (there is
        one namespace, no per-claimant tokens).

        Disaggregated prefill/decode: ``handoff`` carries a decoded
        KV-handoff state (serving/handoff.py) — the worker IMPORTS it as
        committed history and only falls back to a normal admission
        (local recompute, byte-identical) when the import fails.
        ``hold_kv`` marks a prefill-replica request whose finished KV the
        export seam collects (run_in_worker -> engine.export_held). Both
        are ignored under a multihost leader: import/hold on rank 0 alone
        would desynchronize the SPMD lockstep.

        ``resume_outputs``: mid-stream failover — output tokens a dead
        replica already relayed, replayed as forced context when the entry
        admits WITHOUT a usable ``handoff`` (none parked, or the import
        failed): the engine pre-seeds them as output history and the
        stream carries only genuinely new tokens. With ``handoff`` set,
        this is the import's fallback rung — a plain re-prefill of the
        prompt alone would re-emit every already-relayed token."""
        izer = self._interleave
        if izer is not None and izer.decide("generate.submit")[0]:
            # Equivalent to the caller being scheduled later: runs before
            # the reservation consume, so no new await-window opens
            # between a guard and its claim.
            await asyncio.sleep(0)
        if request_id in self._reserved:
            # Consume the slot reserve_request_id claimed for us.
            self._reserved.discard(request_id)
            queue: asyncio.Queue = self._queues[request_id]
        else:
            # Direct (unreserved) callers keep the pre-reservation
            # semantics: a FRESH queue, overwriting any collision — two
            # consumers must never share one queue (the old consumer
            # orphans, exactly as before the reservation seam existed).
            queue = asyncio.Queue()
            self._queues[request_id] = queue
        with self._cv:
            if self.leader is None:
                if handoff is not None:
                    self._handoffs[request_id] = handoff
                if hold_kv:
                    self._holds.add(request_id)
                if arrival_t0 is not None:
                    self._arrival_t0s[request_id] = arrival_t0
                if resume_outputs:
                    self._resumes[request_id] = list(resume_outputs)
            self._inbox.append((request_id, prompt_token_ids, params))
            self._cv.notify()
        try:
            while True:
                chunk = await queue.get()
                if izer is not None and izer.decide("generate.stream")[0]:
                    await asyncio.sleep(0)
                if isinstance(chunk, Exception):
                    raise chunk
                yield chunk
                if chunk.finished:
                    return
        finally:
            self._queues.pop(request_id, None)

    def abort(self, request_id: str) -> None:
        with self._cv:
            self._aborts.append(request_id)
            self._cv.notify()

    def post_exception(self, request_id: str, exc: Exception) -> None:
        """Fail a live stream's consumer with ``exc`` (thread-safe; no-op
        when the queue is gone). The drain-migration driver uses it to
        abort a client connection AFTER its sequence was pushed to a peer
        — the broken relay is the router's failover signal — without
        touching engine state (the export already retired the sequence)."""
        self._post_exc(request_id, exc)

    def run_in_worker(self, fn):
        """Awaitable execution of ``fn(engine)`` on the worker thread —
        the one place engine/scheduler/device state may be touched outside
        step() without racing it (the KV export seam runs here). The
        result (or exception) resolves the returned awaitable."""
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._cv:
            if self._worker_dead():
                # An op enqueued after the worker's final wakeup would never
                # drain and its awaiter would hang forever.
                fut.set_exception(RuntimeError("engine shut down"))
            else:
                self._ops.append((fn, fut))
                self._cv.notify()
        return asyncio.wrap_future(fut)

    def post_to_worker(self, fn) -> None:
        """Fire-and-forget variant of :meth:`run_in_worker` (cleanup from
        handler ``finally`` blocks, where awaiting mid-cancellation is
        unsafe)."""
        with self._cv:
            if self._worker_dead():
                # Engine-side state the op would have cleaned dies with the
                # process anyway; dropping loudly beats a silent no-op.
                logger.warning("worker op dropped: engine shut down")
                return
            self._ops.append((fn, None))
            self._cv.notify()

    def _worker_dead(self) -> bool:
        """Caller holds ``_cv``. True once no future wakeup can drain
        ``_ops``: shutdown requested (the worker's final wakeup fails
        whatever it captured — anything appended later is unreachable), or
        the thread exited (step-crash path; it flags ``_shutdown`` too,
        this also covers a crash mid-unwind)."""
        return self._shutdown or (self._thread.ident is not None
                                  and not self._thread.is_alive())

    # -- worker thread -------------------------------------------------------

    def _worker(self) -> None:
        izer = self._interleave
        while True:
            with self._cv:
                while not (self._shutdown or self._inbox or self._aborts
                           or self._ops
                           or self.engine.has_unfinished_requests()):
                    self._cv.wait()
                inbox, self._inbox = self._inbox, []
                aborts, self._aborts = self._aborts, []
                ops, self._ops = self._ops, []
                if self._shutdown:
                    # Fail pending worker ops loudly: an awaiting export
                    # must not hang past the thread's death.
                    for _, fut in ops:
                        if fut is not None:
                            fut.set_exception(
                                RuntimeError("engine shut down"))
                    return
            if izer is not None:
                # Post-wake, OUTSIDE _cv (a sleep under a loop-contended
                # lock is the KGCT021 bug class itself): widen the window
                # between inbox capture and ops/admission/step.
                izer.worker_yield("worker.wake")
            for fn, fut in ops:
                try:
                    result = fn(self.engine)
                except BaseException as e:
                    if fut is not None:
                        fut.set_exception(e)
                    else:
                        logger.exception("worker op failed")
                else:
                    if fut is not None:
                        fut.set_result(result)
            # A request whose add and abort arrived in the same wakeup must
            # not be admitted: the abort would no-op (nothing to abort yet)
            # and the request would then run orphaned to completion.
            aborted = set(aborts)
            inbox = [item for item in inbox if item[0] not in aborted]
            for rid in aborted:
                self._handoffs.pop(rid, None)
                self._holds.discard(rid)
                self._arrival_t0s.pop(rid, None)
                self._resumes.pop(rid, None)
            if self.leader is not None:
                # Replicate this iteration's events to follower ranks BEFORE
                # stepping: their engines apply the same events and step
                # once, keeping the SPMD collectives in lockstep. A broadcast
                # failure means the process group is broken (a dead follower
                # hangs the collectives anyway): group-abort all in-flight
                # work, fail every waiter loudly, and detach the leader —
                # this rank stays serveable while the StatefulSet restarts
                # the followers (restart-first recovery).
                try:
                    self.leader.broadcast(inbox, aborts)
                except Exception as e:
                    logger.exception("directive broadcast failed; "
                                     "group-aborting in-flight work")
                    # Waiters fail FIRST: the drain below steps an engine
                    # whose process group just broke, and on a real
                    # multi-host mesh those steps can hang on collectives —
                    # clients must not be held hostage to that.
                    err = RuntimeError(
                        f"multihost process group failed: {e}")
                    for rid in list(self._queues):
                        self._post_exc(rid, err)
                    try:
                        self.leader.close()
                    except Exception:
                        pass
                    self.leader = None
                    from .multihost import group_abort
                    # Armed watchdog: if the drain DOES hang on a dead
                    # rank's collectives, /health flips and kubelet
                    # restarts the pod (restart-first recovery) instead of
                    # leaving a healthy-looking zombie.
                    wd = self.watchdog
                    if wd is not None:
                        wd.arm()
                    try:
                        group_abort(self.engine)
                    except Exception:
                        logger.exception("group-abort drain failed")
                    finally:
                        if wd is not None:
                            wd.disarm()
                    continue
            for rid in aborts:
                self.engine.abort_request(rid)
                self._post(StreamChunk(rid, [], [], True, "abort"))
            for rid, ids, params in inbox:
                handoff = self._handoffs.pop(rid, None)
                arrival_t0 = self._arrival_t0s.pop(rid, None)
                resume_outputs = self._resumes.pop(rid, None)
                hold = rid in self._holds
                self._holds.discard(rid)
                try:
                    if handoff is not None:
                        # import_request pops the stamp; keep a copy so an
                        # ENGINE-side import failure backdates the recompute
                        # admission the same way a failed pull does.
                        if arrival_t0 is None:
                            arrival_t0 = handoff.get("_ttft_t0")
                        try:
                            for out in self.engine.import_request(
                                    rid, ids, params, handoff):
                                self._post(_chunk_of(out))
                            continue
                        except Exception as e:
                            # Degrade to local recompute — byte-identical,
                            # just slower; the trace records the fallback.
                            logger.warning(
                                "kv import for %s failed (%s); falling back"
                                " to local prefill", rid, e,
                                extra={"request_id": rid})
                            self.engine.obs.tracer.emit(
                                "handoff", rid, side="import",
                                outcome="import_fallback", error=str(e))
                            if self.on_import_fallback is not None:
                                try:
                                    # rid lets the serving layer attribute
                                    # a MID-STREAM resume import (token-
                                    # replay rung) separately from a
                                    # disagg prefill re-run.
                                    self.on_import_fallback(rid)
                                except Exception:
                                    logger.exception(
                                        "import-fallback hook failed")
                    self.engine.add_request(rid, ids, params, hold_kv=hold,
                                            arrival_t0=arrival_t0,
                                            resume_outputs=resume_outputs)
                except ValueError as e:   # oversized prompt etc.
                    self._post_exc(rid, e)
            if self.engine.has_unfinished_requests():
                if izer is not None:
                    # Between admission and dispatch: the window a loop-
                    # side engine-state read (KGCT020) would race.
                    izer.worker_yield("worker.step")
                wd = self.watchdog
                if wd is not None:
                    wd.arm()
                try:
                    for out in self.engine.step():
                        self._post(_chunk_of(out))
                except Exception as e:  # engine wedged: fail all waiters
                    logger.exception("engine step failed")
                    # Black-box dump: the ring holds the requests/steps that
                    # led here; the pod restarts, the evidence does not.
                    self.engine.obs.flight.dump("engine_step_failed",
                                                error=str(e))
                    if wd is not None:
                        # The loop is about to die: /health must STAY 503
                        # (a disarm here would resurrect health on a server
                        # that can never serve again; kubelet restarts it).
                        wd.mark_dead(f"engine step raised: {e}")
                    for rid in list(self._queues):
                        self._post_exc(rid, e)
                    # The loop is exiting for good: flag shutdown and fail
                    # any ops racing this unwind, so run_in_worker callers
                    # (KV export handlers) never await a drained-by-nobody
                    # future.
                    with self._cv:
                        self._shutdown = True
                        ops, self._ops = self._ops, []
                    for _, fut in ops:
                        if fut is not None:
                            fut.set_exception(
                                RuntimeError(f"engine step raised: {e}"))
                    return
                if wd is not None:
                    wd.disarm()

    def _post(self, chunk: StreamChunk) -> None:
        queue = self._queues.get(chunk.request_id)
        if queue is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(queue.put_nowait, chunk)

    def _post_exc(self, request_id: str, exc: Exception) -> None:
        queue = self._queues.get(request_id)
        if queue is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(queue.put_nowait, exc)


def _chunk_of(out: RequestOutput) -> StreamChunk:
    return StreamChunk(
        request_id=out.request_id,
        new_token_ids=list(out.new_token_ids or []),
        output_token_ids=list(out.output_token_ids),
        finished=out.finished,
        finish_reason=out.finish_reason,
        new_logprobs=list(out.new_logprobs or []),
        new_top_logprobs=list(out.new_top_logprobs or []))
