"""AsyncLLMEngine: asyncio front door over the blocking LLMEngine.

The engine's step() blocks on device sync, so it runs on a dedicated worker
thread; request submission and output streaming cross the thread boundary
through a thread-safe inbox and ``loop.call_soon_threadsafe`` fan-out into
per-request asyncio queues. This is the piece that turns the batch engine
into the always-on serving process behind the OpenAI API (the role vLLM's
AsyncLLMEngine played inside the images the reference deployed,
``old_README.md:1078-1176``).

The worker thread idles on a condition variable when there is no work — an
idle replica burns no CPU and wakes in O(µs) on the first request.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import threading
from typing import AsyncIterator, Optional

from ..config import EngineConfig
from ..engine import LLMEngine, RequestOutput, SamplingParams
from ..utils import get_logger

logger = get_logger("serving.async_engine")


@dataclasses.dataclass
class StreamChunk:
    """One step's worth of progress for a request."""
    request_id: str
    new_token_ids: list[int]
    output_token_ids: list[int]
    finished: bool
    finish_reason: Optional[str]
    new_logprobs: list[float] = dataclasses.field(default_factory=list)
    new_top_logprobs: list = dataclasses.field(default_factory=list)


class AsyncLLMEngine:
    def __init__(self, config: EngineConfig, params=None,
                 eos_token_id: Optional[int] = None, mesh=None,
                 leader=None):
        """``leader``: serving.multihost.DirectiveLeader when this process
        is rank 0 of a multi-process mesh — every worker-loop iteration's
        (adds, aborts) are broadcast to follower ranks BEFORE the local
        apply+step so all engines schedule in SPMD lockstep."""
        self.engine = LLMEngine(config, params=params,
                                eos_token_id=eos_token_id, mesh=mesh)
        self.leader = leader
        # resilience.StepWatchdog, set by APIServer: armed around each
        # step() so a hung device dispatch flips /health instead of parking
        # requests forever behind a 200-ok server.
        self.watchdog = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: dict[str, asyncio.Queue] = {}
        # Ids reserved via reserve_request_id whose generate() has not
        # started yet — lets release_reservation() free a slot the handler
        # abandoned (client died between reserve and first iteration)
        # without ever touching a live generator's queue.
        self._reserved: set = set()
        self._inbox: list = []            # (request_id, token_ids, params)
        self._aborts: list[str] = []
        self._cv = threading.Condition()
        self._shutdown = False
        self._counter = itertools.count()
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="kgct-engine-step-loop")

    # -- lifecycle -----------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        # get_running_loop, not get_event_loop: the fan-out posts chunks
        # via call_soon_threadsafe, and a loop silently CREATED here (off
        # the server's thread, never run) would swallow them forever —
        # kgct-lint KGCT006 pins this.
        self._loop = loop if loop is not None else asyncio.get_running_loop()
        self._thread.start()

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify()
        self._thread.join(timeout=30)
        if self.leader is not None:
            if self._thread.is_alive():
                # A wedged worker may still write the directive sockets;
                # closing now would interleave frames and corrupt the
                # follower's NDJSON stream. Leave the sockets to the OS.
                logger.warning("worker thread still alive after join "
                               "timeout; skipping leader close")
            else:
                self.leader.close()

    # -- request API ---------------------------------------------------------

    def next_request_id(self, prefix: str = "cmpl") -> str:
        return f"{prefix}-{next(self._counter)}"

    def reserve_request_id(self, request_id: str) -> bool:
        """Atomically claim ``request_id``'s output-queue slot (False if a
        live request already holds it). Synchronous on the event-loop
        thread — no await between check and claim — so the API layer calls
        this immediately before ``generate()`` and a concurrent duplicate
        of a client-supplied correlation id can never cross streams (an
        async-generator-side check would only run at first iteration,
        AFTER the caller's awaits — the TOCTOU this closes). Callers must
        pair it with :meth:`release_reservation` on every handler exit
        path, or an abandoned reservation would mark the id in-flight
        forever."""
        if request_id in self._queues:
            return False
        self._queues[request_id] = asyncio.Queue()
        self._reserved.add(request_id)
        return True

    def release_reservation(self, request_id: str) -> bool:
        """Free a reservation whose ``generate()`` never STARTED (the
        handler died between reserve and the generator's first iteration —
        e.g. ``resp.prepare`` raising on client disconnect). A no-op once
        the generator consumed the reservation: its own finally owns the
        queue's lifetime from then on.

        Returns True when a reservation WAS released — the engine never saw
        the request, so the caller must NOT enqueue an abort for it: a
        stale abort of a reused client-supplied id would terminate (or
        orphan) a LATER request that legitimately claims the same id."""
        if request_id in self._reserved:
            self._reserved.discard(request_id)
            self._queues.pop(request_id, None)
            return True
        return False

    async def generate(self, request_id: str, prompt_token_ids: list[int],
                       params: SamplingParams) -> AsyncIterator[StreamChunk]:
        """Submit a request and yield StreamChunks until finished.

        Id contract: serving callers reserve the id first (see
        reserve_request_id, looped until owned); a DIRECT caller must use
        an id it knows to be unique — calling with an id that has a
        pending reservation would consume the reserver's slot (there is
        one namespace, no per-claimant tokens)."""
        if request_id in self._reserved:
            # Consume the slot reserve_request_id claimed for us.
            self._reserved.discard(request_id)
            queue: asyncio.Queue = self._queues[request_id]
        else:
            # Direct (unreserved) callers keep the pre-reservation
            # semantics: a FRESH queue, overwriting any collision — two
            # consumers must never share one queue (the old consumer
            # orphans, exactly as before the reservation seam existed).
            queue = asyncio.Queue()
            self._queues[request_id] = queue
        with self._cv:
            self._inbox.append((request_id, prompt_token_ids, params))
            self._cv.notify()
        try:
            while True:
                chunk = await queue.get()
                if isinstance(chunk, Exception):
                    raise chunk
                yield chunk
                if chunk.finished:
                    return
        finally:
            self._queues.pop(request_id, None)

    def abort(self, request_id: str) -> None:
        with self._cv:
            self._aborts.append(request_id)
            self._cv.notify()

    # -- worker thread -------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not (self._shutdown or self._inbox or self._aborts
                           or self.engine.has_unfinished_requests()):
                    self._cv.wait()
                if self._shutdown:
                    return
                inbox, self._inbox = self._inbox, []
                aborts, self._aborts = self._aborts, []
            # A request whose add and abort arrived in the same wakeup must
            # not be admitted: the abort would no-op (nothing to abort yet)
            # and the request would then run orphaned to completion.
            aborted = set(aborts)
            inbox = [item for item in inbox if item[0] not in aborted]
            if self.leader is not None:
                # Replicate this iteration's events to follower ranks BEFORE
                # stepping: their engines apply the same events and step
                # once, keeping the SPMD collectives in lockstep. A broadcast
                # failure means the process group is broken (a dead follower
                # hangs the collectives anyway): group-abort all in-flight
                # work, fail every waiter loudly, and detach the leader —
                # this rank stays serveable while the StatefulSet restarts
                # the followers (restart-first recovery).
                try:
                    self.leader.broadcast(inbox, aborts)
                except Exception as e:
                    logger.exception("directive broadcast failed; "
                                     "group-aborting in-flight work")
                    # Waiters fail FIRST: the drain below steps an engine
                    # whose process group just broke, and on a real
                    # multi-host mesh those steps can hang on collectives —
                    # clients must not be held hostage to that.
                    err = RuntimeError(
                        f"multihost process group failed: {e}")
                    for rid in list(self._queues):
                        self._post_exc(rid, err)
                    try:
                        self.leader.close()
                    except Exception:
                        pass
                    self.leader = None
                    from .multihost import group_abort
                    # Armed watchdog: if the drain DOES hang on a dead
                    # rank's collectives, /health flips and kubelet
                    # restarts the pod (restart-first recovery) instead of
                    # leaving a healthy-looking zombie.
                    wd = self.watchdog
                    if wd is not None:
                        wd.arm()
                    try:
                        group_abort(self.engine)
                    except Exception:
                        logger.exception("group-abort drain failed")
                    finally:
                        if wd is not None:
                            wd.disarm()
                    continue
            for rid in aborts:
                self.engine.abort_request(rid)
                self._post(StreamChunk(rid, [], [], True, "abort"))
            for rid, ids, params in inbox:
                try:
                    self.engine.add_request(rid, ids, params)
                except ValueError as e:   # oversized prompt etc.
                    self._post_exc(rid, e)
            if self.engine.has_unfinished_requests():
                wd = self.watchdog
                if wd is not None:
                    wd.arm()
                try:
                    for out in self.engine.step():
                        self._post(_chunk_of(out))
                except Exception as e:  # engine wedged: fail all waiters
                    logger.exception("engine step failed")
                    # Black-box dump: the ring holds the requests/steps that
                    # led here; the pod restarts, the evidence does not.
                    self.engine.obs.flight.dump("engine_step_failed",
                                                error=str(e))
                    if wd is not None:
                        # The loop is about to die: /health must STAY 503
                        # (a disarm here would resurrect health on a server
                        # that can never serve again; kubelet restarts it).
                        wd.mark_dead(f"engine step raised: {e}")
                    for rid in list(self._queues):
                        self._post_exc(rid, e)
                    return
                if wd is not None:
                    wd.disarm()

    def _post(self, chunk: StreamChunk) -> None:
        queue = self._queues.get(chunk.request_id)
        if queue is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(queue.put_nowait, chunk)

    def _post_exc(self, request_id: str, exc: Exception) -> None:
        queue = self._queues.get(request_id)
        if queue is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(queue.put_nowait, exc)


def _chunk_of(out: RequestOutput) -> StreamChunk:
    return StreamChunk(
        request_id=out.request_id,
        new_token_ids=list(out.new_token_ids or []),
        output_token_ids=list(out.output_token_ids),
        finished=out.finished,
        finish_reason=out.finish_reason,
        new_logprobs=list(out.new_logprobs or []),
        new_top_logprobs=list(out.new_top_logprobs or []))
