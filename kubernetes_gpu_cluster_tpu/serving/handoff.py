"""KV-handoff wire protocol for disaggregated prefill/decode serving.

The decode replica pulls a finished prefill's committed KV pages plus the
sequence state from the prefill replica's ``POST /internal/kv_handoff``
endpoint and imports them as committed history (``LLMEngine.import_request``
— the swap-in path, no prefill replay). This module owns the two halves the
api_server composes:

- the BLOB codec: one self-describing binary frame — magic, a bounded JSON
  header (sequence state + array shapes/dtype), then the raw ``k`` and
  ``v`` buffer bytes. No pickle anywhere: the decode side reconstructs the
  arrays with ``np.frombuffer`` from the header's declared shape/dtype, so
  a malicious or corrupt payload can fail validation but never execute.
  ``tobytes``/``frombuffer`` round-trip every dtype the pool can use,
  including ``bfloat16`` (ml_dtypes registers it with numpy);
- the BOUNDED fetch: the puller caps both the response size (a handoff can
  never legitimately exceed the local pool's own byte size) and the wall
  time, so one wedged prefill replica cannot hang or balloon a decode
  replica — any failure degrades to local recompute, which is
  byte-identical, just slower (chaos site ``kv_handoff_fail`` forces that
  path deterministically).

Every codec here (handoff frame, prefix stream, spill frame) additionally
speaks a versioned INTEGRITY extension: with ``integrity=True`` the JSON
header carries per-page CRC32 checksums over the K|V slabs plus a
whole-frame digest, verified on decode (incrementally, for the streamed
prefix codec) and re-verified at the import seam by
:func:`verify_import_state` right before the engine commit. A mismatch
raises :class:`WireCorruptionError`; a pre-integrity peer talking to a
receiver that requires checksums raises :class:`ProtocolSkewError` (both
ValueError subclasses, so every degrade-to-recompute path is unchanged).
Integrity OFF emits byte-identical pre-extension frames — mixed fleets
interoperate during rollout and the checksum cost is a measurable A/B.

Everything here is engine-free and jax-free so tests can pin the codec and
the fetch discipline without building an engine.
"""

from __future__ import annotations

import json
import struct
import time
import zlib
from typing import Optional

import aiohttp
import numpy as np

from .errors import REQUEST_ID_HEADER


class WireCorruptionError(ValueError):
    """A frame whose bytes do not match its own declared checksums: a
    bit-flip in transit, a truncation that still parses, or a peer that
    serves stale pages under a fresh header. Subclasses ValueError so every
    existing degrade-to-recompute catch handles it unchanged; callers that
    care (metrics attribution, peer scoreboards) can still distinguish."""


class ProtocolSkewError(ValueError):
    """A peer speaking the pre-integrity wire dialect to a receiver that
    requires checksums (or vice versa at a receive seam): the frames are not
    worth a decode attempt — the negotiation failure is the finding. HTTP
    seams translate this to a 426-style rejection instead of a decode."""


def _page_crcs(arr) -> list:
    """Per-page CRC32 of one KV array laid out ``[L, n_pages, ps, kd]``:
    page ``p``'s checksum folds over every layer's contiguous ``[ps, kd]``
    slab, exactly the bytes that land on the wire for that page whatever
    codec (whole-frame or chunked) carried them. Byte-view fold — no
    per-page temporaries, dtype-agnostic (bfloat16 included)."""
    a = np.ascontiguousarray(arr)
    b = a.view(np.uint8)
    out = []
    for p in range(a.shape[1]):
        c = 0
        for layer in range(a.shape[0]):
            c = zlib.crc32(b[layer, p], c)
        out.append(c)
    return out


def _frame_crc(k_crcs: list, v_crcs: list, payload_bytes: int) -> int:
    """Whole-frame digest: CRC32 over the packed per-page checksum lists
    plus the payload byte count. Covers the integrity metadata itself — a
    header whose crc list was altered in transit fails here before any
    per-page compare can be fooled."""
    packed = struct.pack(f">{len(k_crcs)}I", *k_crcs) \
        + struct.pack(f">{len(v_crcs)}I", *v_crcs) \
        + struct.pack(">Q", payload_bytes)
    return zlib.crc32(packed)


def _check_integrity_header(header: dict, n_pages: int, payload_bytes: int,
                            require: bool, what: str):
    """Pop and validate the integrity fields of a decoded JSON header.
    Returns ``(k_crcs, v_crcs)`` or ``None`` when the frame carries no
    integrity fields (pre-integrity dialect) and ``require`` is False.
    Raises :class:`ProtocolSkewError` when required-but-absent, and
    :class:`WireCorruptionError` on a malformed or self-inconsistent
    integrity header (wrong list lengths, frame digest mismatch)."""
    pc = header.pop("page_crc", None)
    fc = header.pop("frame_crc", None)
    if pc is None or fc is None:
        if require:
            raise ProtocolSkewError(
                f"{what}: peer speaks the pre-integrity wire dialect "
                "(no page_crc/frame_crc header fields)")
        return None
    try:
        k_crcs = [int(c) for c in pc["k"]]
        v_crcs = [int(c) for c in pc["v"]]
    except (TypeError, KeyError, ValueError):
        raise WireCorruptionError(
            f"{what}: malformed page_crc header") from None
    if len(k_crcs) != n_pages or len(v_crcs) != n_pages:
        raise WireCorruptionError(
            f"{what}: page_crc lists cover {len(k_crcs)}/{len(v_crcs)} "
            f"pages, frame carries {n_pages}")
    if _frame_crc(k_crcs, v_crcs, payload_bytes) != int(fc):
        raise WireCorruptionError(f"{what}: frame digest mismatch")
    return k_crcs, v_crcs


def verify_import_state(state: dict) -> None:
    """The import-seam verify: re-checksum the K/V arrays of a decoded
    state dict against the integrity stash its decode left behind
    (``_integrity``), popping the stash either way so the engine's import
    validation never sees it. Called immediately before every
    ``import_request``-family commit — the last line of defense between a
    frame that decoded clean and pages entering the pool. No-op for frames
    that carried no integrity fields (integrity off / pre-integrity peer).
    Raises :class:`WireCorruptionError` naming the first bad page."""
    integ = state.pop("_integrity", None)
    if integ is None:
        return
    for name in ("k", "v"):
        want = integ[name]
        got = _page_crcs(state[name])
        if got != want:
            bad = next(i for i, (g, w) in enumerate(zip(got, want))
                       if g != w)
            raise WireCorruptionError(
                f"import state: {name} page {bad} checksum mismatch")

# Frame: MAGIC + u32 header length + JSON header + k bytes + v bytes.
HANDOFF_MAGIC = b"KGCT-KV1"
# A JSON header larger than this is corrupt, not big: it carries token id
# lists and scalars, never KV content.
HEADER_MAX_BYTES = 8 << 20
# Wall bound for one pull (connect + prefill compute + transfer). Generous:
# the prefill replica may be running a long prompt; a decode replica that
# gives up just recomputes locally.
HANDOFF_TIMEOUT_S = 120.0

# Client body fields the decode replica forwards so the prefill replica
# samples the FIRST token exactly as a colocated engine would (penalties see
# no output yet; seed/temperature/bias shape the very first sample).
FORWARDED_SAMPLING_FIELDS = (
    "temperature", "top_p", "top_k", "seed", "presence_penalty",
    "frequency_penalty", "logit_bias", "stop_token_ids", "logprobs",
    # QoS tenant keys: the prefill replica resolves the request's tier
    # from them (user-pin > default — the pull carries no client
    # headers), so a batch prompt's remote prefill competes in the
    # prefill pool's own fair-share scheduler as batch work, not as
    # default-tier work.
    "session_id", "user",
)


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype NAME (including the ml_dtypes families numpy alone
    does not know, e.g. bfloat16) without importing jax."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_handoff(state: dict, integrity: bool = False) -> bytearray:
    """Engine export dict (``LLMEngine.export_held``) -> one binary frame.

    The arrays are copied straight into their slices of one preallocated
    buffer — no ``tobytes`` temporaries, no join copy — so a concurrent
    burst of exports peaks at the frames themselves, not ~3x the KV bytes
    (returns ``bytearray`` for that reason; every consumer — aiohttp
    response body, ``decode_handoff`` — takes any bytes-like).

    ``integrity`` stamps the versioned integrity extension into the header
    (per-page CRC32 lists + whole-frame digest). Off = byte-identical to
    the pre-integrity frame, so mixed fleets interoperate during a rollout
    and the knob's cost is measurable as a pure A/B."""
    k, v = state["k"], state["v"]
    header = dict(state)
    header.pop("k")
    header.pop("v")
    header["k_shape"] = list(k.shape)
    if integrity:
        k_crcs, v_crcs = _page_crcs(k), _page_crcs(v)
        header["page_crc"] = {"k": k_crcs, "v": v_crcs}
        header["frame_crc"] = _frame_crc(k_crcs, v_crcs,
                                         k.nbytes + v.nbytes)
    header_bytes = json.dumps(header).encode()
    off = len(HANDOFF_MAGIC) + 4 + len(header_bytes)
    out = bytearray(off + k.nbytes + v.nbytes)
    out[:off] = HANDOFF_MAGIC + struct.pack(">I", len(header_bytes)) \
        + header_bytes
    view = memoryview(out)
    np.copyto(np.frombuffer(view, k.dtype, count=k.size,
                            offset=off).reshape(k.shape), k)
    np.copyto(np.frombuffer(view, v.dtype, count=v.size,
                            offset=off + k.nbytes).reshape(v.shape), v)
    return out


def decode_handoff(data: bytes | bytearray,
                   require_integrity: bool = False) -> dict:
    """Binary frame -> the engine import state dict. Raises ValueError on
    any structural mismatch (truncated frame, oversized header, byte-count
    drift) — the caller treats that as a failed handoff and recomputes.

    Frames carrying the integrity extension are checksum-verified here
    (frame digest, then every page of K and V) and the per-page list is
    stashed under ``_integrity`` so :func:`verify_import_state` can
    re-check at the import seam right before the engine commit.
    ``require_integrity`` rejects pre-integrity frames with
    :class:`ProtocolSkewError` instead of silently trusting them."""
    m = len(HANDOFF_MAGIC)
    if data[:m] != HANDOFF_MAGIC:
        raise ValueError("handoff blob: bad magic")
    if len(data) < m + 4:
        raise ValueError("handoff blob: truncated header length")
    (hlen,) = struct.unpack(">I", data[m:m + 4])
    if hlen > HEADER_MAX_BYTES:
        raise ValueError(f"handoff blob: header {hlen} bytes exceeds bound")
    off = m + 4
    try:
        header = json.loads(data[off:off + hlen])
    except ValueError as e:
        raise ValueError(f"handoff blob: bad header JSON ({e})") from None
    off += hlen
    shape = tuple(int(d) for d in header.pop("k_shape"))
    dtype = _np_dtype(str(header["dtype"]))
    nbytes = int(np.prod(shape)) * dtype.itemsize
    if len(data) != off + 2 * nbytes:
        raise ValueError(
            f"handoff blob: payload {len(data) - off} bytes != 2 x {nbytes}")
    crcs = _check_integrity_header(header, int(shape[1]), 2 * nbytes,
                                   require_integrity, "handoff blob")
    header["k"] = np.frombuffer(data, dtype, count=int(np.prod(shape)),
                                offset=off).reshape(shape)
    header["v"] = np.frombuffer(data, dtype, count=int(np.prod(shape)),
                                offset=off + nbytes).reshape(shape)
    if crcs is not None:
        k_crcs, v_crcs = crcs
        for name, arr, want in (("k", header["k"], k_crcs),
                                ("v", header["v"], v_crcs)):
            got = _page_crcs(arr)
            if got != want:
                bad = next(i for i, (g, w) in enumerate(zip(got, want))
                           if g != w)
                raise WireCorruptionError(
                    f"handoff blob: {name} page {bad} checksum mismatch")
        header["_integrity"] = {"k": k_crcs, "v": v_crcs}
    return header


def handoff_request_body(prompt_token_ids: list, body: dict) -> dict:
    """The JSON body a decode replica sends the prefill replica: the
    already-tokenized prompt (the prefill side must not re-tokenize — text
    normalization drift would change the KV) plus the sampling fields that
    shape the first token."""
    fwd = {"prompt_token_ids": list(prompt_token_ids)}
    for field in FORWARDED_SAMPLING_FIELDS:
        if field in body and body[field] is not None:
            fwd[field] = body[field]
    return fwd


# -- fleet-prefix stream codec (global prefix cache over this substrate) ----
#
# The sequence-handoff frame above is one blob: header, all K bytes, all V
# bytes — fine for a one-sequence import that joins ``running`` atomically.
# A FLEET-CACHE prefix pull wants the opposite: the importer scatters pages
# as they arrive off the socket (each chunk one worker op, interleaving
# with other requests' decode steps), so the wire layout interleaves K and
# V per page-chunk instead of splitting them at the frame's midpoint.
# Frame: PREFIX_MAGIC + u32 header length + JSON header (model/page_size/
# dtype/matched_tokens/prompt_token_ids/k_shape/chunk_pages) + one
# [k_chunk][v_chunk] slab per chunk of ``chunk_pages`` pages (the last
# chunk may be short). No pickle, same discipline as the handoff frame.

PREFIX_MAGIC = b"KGCT-PF1"

# Pages per streamed chunk: small enough that a chunk scatter never blocks
# the worker loop noticeably, large enough that per-chunk op overhead stays
# negligible next to the copy.
PREFIX_CHUNK_PAGES = 4

# Wall bound for one prefix pull. Much tighter than the sequence-handoff
# pull: no prefill compute hides inside it (the pages are already cached on
# the owner) — it is connect + gather + transfer, and a replica that gives
# up just recomputes locally.
PREFIX_PULL_TIMEOUT_S = 30.0


def encode_prefix_frames(state: dict,
                         chunk_pages: int = PREFIX_CHUNK_PAGES,
                         integrity: bool = False):
    """Engine export dict (``LLMEngine.export_prefix``) -> an iterator of
    wire slabs: the header first, then one contiguous ``[k|v]`` slab per
    page chunk. The exporter writes each slab straight to the response so
    the importer can start scattering before the tail pages even left the
    owner's socket. ``integrity`` stamps the per-page CRC lists + frame
    digest into the header so the decoder verifies each chunk as it
    completes; off = byte-identical to the pre-integrity stream."""
    k, v = state["k"], state["v"]
    header = {key: val for key, val in state.items()
              if key not in ("k", "v")}
    header["k_shape"] = list(k.shape)
    header["chunk_pages"] = int(chunk_pages)
    if integrity:
        k_crcs, v_crcs = _page_crcs(k), _page_crcs(v)
        header["page_crc"] = {"k": k_crcs, "v": v_crcs}
        header["frame_crc"] = _frame_crc(k_crcs, v_crcs,
                                         k.nbytes + v.nbytes)
    hb = json.dumps(header).encode()
    yield PREFIX_MAGIC + struct.pack(">I", len(hb)) + hb
    n = k.shape[1]
    for i in range(0, n, chunk_pages):
        ck, cv = k[:, i:i + chunk_pages], v[:, i:i + chunk_pages]
        slab = bytearray(ck.nbytes + cv.nbytes)
        view = memoryview(slab)
        np.copyto(np.frombuffer(view, ck.dtype,
                                count=ck.size).reshape(ck.shape),
                  np.ascontiguousarray(ck))
        np.copyto(np.frombuffer(view, cv.dtype, count=cv.size,
                                offset=ck.nbytes).reshape(cv.shape),
                  np.ascontiguousarray(cv))
        yield slab


class PrefixStreamDecoder:
    """Incremental decoder of the prefix stream: feed socket chunks in,
    get (k_chunk, v_chunk) page slabs out as soon as each completes.
    ``header`` is available once the first feed crossed the header
    boundary; ``done`` once every advertised page was yielded. Raises
    ValueError on any structural mismatch (bad magic, oversized header,
    trailing bytes) — the importer aborts and recomputes.

    Integrity: a stream whose header carries the checksum extension is
    verified INCREMENTALLY — each chunk's pages are checksummed the moment
    the chunk completes, BEFORE the importer can scatter it, so a
    corrupted tail chunk aborts with the head chunks the only pages to
    free (:class:`WireCorruptionError` at the corrupt chunk).
    ``require_integrity`` rejects pre-integrity streams with
    :class:`ProtocolSkewError` at the header."""

    def __init__(self, require_integrity: bool = False):
        # bytearray: += is amortized O(1). An immutable bytes buffer
        # would memcpy the whole accumulated slab on EVERY socket chunk —
        # quadratic in slab size, ruinous at real-model page geometry.
        self._buf = bytearray()
        self.header: Optional[dict] = None
        self._shape = None          # (L, n_pages, ps, kd)
        self._dtype = None
        self._chunk_pages = 0
        self._yielded_pages = 0
        self._require_integrity = require_integrity
        self._crcs = None           # (k_crcs, v_crcs) when integrity on

    @property
    def done(self) -> bool:
        return (self._shape is not None
                and self._yielded_pages >= self._shape[1])

    def _try_header(self) -> None:
        m = len(PREFIX_MAGIC)
        if len(self._buf) < m + 4:
            return
        if self._buf[:m] != PREFIX_MAGIC:
            raise ValueError("prefix stream: bad magic")
        (hlen,) = struct.unpack(">I", self._buf[m:m + 4])
        if hlen > HEADER_MAX_BYTES:
            raise ValueError(
                f"prefix stream: header {hlen} bytes exceeds bound")
        if len(self._buf) < m + 4 + hlen:
            return
        try:
            header = json.loads(bytes(self._buf[m + 4:m + 4 + hlen]))
        except ValueError as e:
            raise ValueError(
                f"prefix stream: bad header JSON ({e})") from None
        # Missing/garbage fields must surface as ValueError — the one
        # exception class every caller's degrade-to-recompute (and the
        # spill handler's 400) catches; a KeyError here would escape as
        # an unhandled 500.
        try:
            shape = tuple(int(d) for d in header.pop("k_shape"))
            self._chunk_pages = int(header.pop("chunk_pages", 0))
            dtype = _np_dtype(str(header["dtype"]))
        except ValueError:
            raise
        except Exception as e:
            raise ValueError(
                f"prefix stream: malformed header ({e!r})") from None
        if len(shape) != 4 or any(d < 1 for d in shape):
            raise ValueError(f"prefix stream: bad k_shape {shape}")
        if self._chunk_pages < 1:
            raise ValueError("prefix stream: bad chunk_pages")
        payload = 2 * int(np.prod(shape)) * dtype.itemsize
        self._crcs = _check_integrity_header(
            header, int(shape[1]), payload, self._require_integrity,
            "prefix stream")
        self._shape = shape
        self._dtype = dtype
        self.header = header
        del self._buf[:m + 4 + hlen]

    def feed(self, data: bytes) -> list:
        """Returns the list of (k_chunk, v_chunk) arrays completed by this
        feed, each of shape ``[L, c, ps, kd]``. Copies out of the buffer so
        the arrays stay valid after further feeds."""
        self._buf += data
        if self.header is None:
            self._try_header()
            if self.header is None:
                return []
        out = []
        L, n, ps, kd = self._shape
        per_page = L * ps * kd * self._dtype.itemsize
        while self._yielded_pages < n:
            c = min(self._chunk_pages, n - self._yielded_pages)
            slab = 2 * c * per_page
            if len(self._buf) < slab:
                break
            view = bytes(self._buf[:slab])
            ck = np.frombuffer(view, self._dtype,
                               count=c * per_page // self._dtype.itemsize
                               ).reshape(L, c, ps, kd)
            cv = np.frombuffer(view, self._dtype,
                               count=c * per_page // self._dtype.itemsize,
                               offset=c * per_page
                               ).reshape(L, c, ps, kd)
            if self._crcs is not None:
                start = self._yielded_pages
                for name, arr, want in (("k", ck, self._crcs[0]),
                                        ("v", cv, self._crcs[1])):
                    got = _page_crcs(arr)
                    if got != want[start:start + c]:
                        bad = start + next(
                            i for i, (g, w) in enumerate(
                                zip(got, want[start:start + c])) if g != w)
                        raise WireCorruptionError(
                            f"prefix stream: {name} page {bad} checksum "
                            "mismatch")
            out.append((ck, cv))
            del self._buf[:slab]
            self._yielded_pages += c
        if self.done and self._buf:
            raise ValueError(
                f"prefix stream: {len(self._buf)} trailing bytes")
        return out


def encode_spill_frame(digest_hex: str, k_np: np.ndarray,
                       v_np: np.ndarray, model: str, page_size: int,
                       integrity: bool = False) -> bytes:
    """One remote-spilled page -> one prefix-stream frame (single chunk)
    whose header carries the chained digest instead of token ids — the
    receiver parks it in its HOST tier keyed by the digest
    (``LLMEngine.accept_remote_spill``)."""
    state = {"model": model, "page_size": page_size,
             "dtype": str(k_np.dtype), "digest": digest_hex,
             "k": k_np, "v": v_np}
    return b"".join(bytes(part) for part in
                    encode_prefix_frames(state, chunk_pages=1,
                                         integrity=integrity))


def decode_spill_frame(data: bytes, require_integrity: bool = False
                       ) -> tuple[str, dict, np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_spill_frame`: (digest_hex, header, k, v).
    Raises ValueError on any mismatch (checksum mismatches as
    :class:`WireCorruptionError`, pre-integrity frames under
    ``require_integrity`` as :class:`ProtocolSkewError`)."""
    dec = PrefixStreamDecoder(require_integrity=require_integrity)
    chunks = dec.feed(data)
    if dec.header is None or not dec.done or len(chunks) != 1:
        raise ValueError("spill frame: truncated or multi-chunk")
    digest = dec.header.get("digest")
    if not isinstance(digest, str):
        raise ValueError("spill frame: missing digest")
    return digest, dec.header, chunks[0][0], chunks[0][1]


# Wall bound for one mid-stream migration PUSH (connect + transfer). Much
# tighter than the pull bound: the blob is already in host memory — no
# prefill compute hides inside it — and every second here extends the
# drain. A push that misses the bound falls back to wait-it-out.
MIGRATE_PUSH_TIMEOUT_S = 20.0

# Parked-migration bounds: a receiving replica holds at most this many
# mid-stream states, each for at most this long, before the router's
# failover re-dispatch claims it (or never comes — client gone).
MIGRATION_PARK_CAP = 64
MIGRATION_PARK_TTL_S = 120.0


class MigrationStore:
    """Bounded parking lot for pushed mid-stream migration states on the
    RECEIVING replica: a drain push parks the decoded state dict here (host
    memory only — no device pages are spent on a stream whose client may
    never fail over); the router's ``/internal/resume`` re-dispatch claims
    it by request id and imports it then. Entries expire by TTL and the
    store is capacity-bounded (oldest evicted first) so a misbehaving or
    crashing fleet cannot balloon a healthy replica. Engine-free and
    jax-free, like the codec."""

    def __init__(self, cap: int = MIGRATION_PARK_CAP,
                 ttl_s: float = MIGRATION_PARK_TTL_S,
                 clock=None):
        self.cap = cap
        self.ttl_s = ttl_s
        self._clock = clock if clock is not None else time.monotonic
        self._entries: dict[str, tuple[float, dict]] = {}

    def __len__(self) -> int:
        self._expire()
        return len(self._entries)

    def _expire(self) -> None:
        now = self._clock()
        dead = [rid for rid, (deadline, _) in self._entries.items()
                if deadline <= now]
        for rid in dead:
            del self._entries[rid]

    def put(self, request_id: str, state: dict) -> None:
        self._expire()
        # A re-push for the same id replaces (the newer snapshot wins);
        # otherwise evict oldest-deadline entries to stay under cap.
        self._entries.pop(request_id, None)
        while len(self._entries) >= self.cap:
            oldest = min(self._entries, key=lambda r: self._entries[r][0])
            del self._entries[oldest]
        self._entries[request_id] = (self._clock() + self.ttl_s, state)

    def pop(self, request_id: str) -> Optional[dict]:
        self._expire()
        entry = self._entries.pop(request_id, None)
        return entry[1] if entry is not None else None


async def push_handoff(session: aiohttp.ClientSession, peer_url: str,
                       blob, request_id: str,
                       timeout_s: float = MIGRATE_PUSH_TIMEOUT_S) -> None:
    """POST a mid-stream migration blob to ``peer_url``'s
    ``/internal/kv_handoff`` (the push direction of the same endpoint the
    disaggregated pull uses; the octet-stream content type selects it).
    Raises on any non-200 or timeout — the caller falls back to keeping
    the sequence local (wait-it-out drain)."""
    async with session.post(
            f"{peer_url.rstrip('/')}/internal/kv_handoff", data=blob,
            headers={REQUEST_ID_HEADER: request_id,
                     "Content-Type": "application/octet-stream"},
            timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
        if resp.status != 200:
            snippet = (await resp.content.read(2048)).decode(
                "utf-8", errors="replace")
            raise RuntimeError(
                f"migration push rejected {resp.status}: {snippet[:200]}")
        await resp.read()


async def fetch_handoff(session: aiohttp.ClientSession, prefill_url: str,
                        payload: dict, request_id: str, max_bytes: int,
                        timeout_s: float = HANDOFF_TIMEOUT_S,
                        qos_tier: str = None) -> bytes:
    """POST the handoff request and read the blob with both bounds applied.
    Raises on any non-200, oversized, or timed-out response — the caller
    falls back to local recompute. ``qos_tier``: the decode replica's
    RESOLVED tier, forwarded so a header-classed request keeps its class
    on the prefill replica (the tenant-key fields in the payload only
    cover user-pin resolution)."""
    headers = {REQUEST_ID_HEADER: request_id}
    if qos_tier is not None:
        from .errors import QOS_TIER_HEADER
        headers[QOS_TIER_HEADER] = qos_tier
    async with session.post(
            f"{prefill_url.rstrip('/')}/internal/kv_handoff", json=payload,
            headers=headers,
            timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
        if resp.status != 200:
            # Bounded error peek: the envelope is small; never slurp an
            # unbounded error body into memory.
            snippet = (await resp.content.read(2048)).decode(
                "utf-8", errors="replace")
            raise RuntimeError(
                f"handoff upstream {resp.status}: {snippet[:200]}")
        if resp.content_length is not None and \
                resp.content_length > max_bytes:
            raise RuntimeError(
                f"handoff blob {resp.content_length} bytes exceeds the "
                f"local bound {max_bytes}")
        data = await resp.content.read(max_bytes + 1)
        if len(data) > max_bytes:
            raise RuntimeError(
                f"handoff blob exceeds the local bound {max_bytes}")
        return data
