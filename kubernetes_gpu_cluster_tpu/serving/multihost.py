"""Multihost SPMD serving: step-directive replication from rank 0.

Under a mesh that spans processes (the rendered StatefulSet: one engine pod
per host, jax.distributed over ICI/DCN), every rank must run the SAME
engine-step sequence — the jitted step enters collectives, and a rank that
steps alone hangs the process group. But only rank 0 receives client
traffic (the Service pins to pod-index 0, deploy/render.py). The reference
solved this with Ray: the vLLM driver shipped work to workers
(old_README.md:1615-1625). TPU-native replacement:

- The engine's host-side scheduler is DETERMINISTIC given the sequence of
  (admissions, aborts) applied at each step boundary, so lockstep needs
  only that event stream — not tensors, not tokens.
- Rank 0 (leader) broadcasts one DIRECTIVE per worker-loop iteration —
  ``{"adds": [(rid, token_ids, sampling_params)], "aborts": [rid]}`` as one
  NDJSON line over a persistent TCP connection to every follower — BEFORE
  taking its own step, then steps; device collectives do the actual
  synchronization (a lagging follower simply makes the leader's collective
  wait).
- Followers (rank > 0) run no HTTP server: they accept the leader's
  connection, and for each directive apply the events and take exactly one
  engine step. Same config + same seed => identical scheduling, identical
  step programs, lockstep collectives.

Failure model: a dead follower breaks the jax.distributed process group
anyway (collectives hang), so directive-connection errors trigger a CLEAN
group abort — every queued/running request is aborted and its pages
released before the rank exits or detaches — and the StatefulSet restarts
the group, matching the reference's reset-first recovery story (SURVEY
§5.3). Liveness is symmetric:

- leader -> follower HEARTBEATS (``{"hb": 1}`` lines on the directive
  channel, resilience-config cadence) keep an idle group's followers able
  to distinguish "no work" from "dead leader";
- a follower whose channel is silent past ``liveness_timeout_s`` declares
  the leader dead, group-aborts, and flips its health endpoint
  (``LoopLiveness``) so kubelet restarts the rank;
- a leader whose heartbeat send fails surfaces the error on the next
  ``broadcast`` — the serving loop group-aborts there.

Chaos site (resilience.faults): ``broadcast_fail`` makes the next leader
broadcast raise, exercising the whole group-abort path without killing a
real rank.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
from typing import Optional

from ..engine import LLMEngine, SamplingParams
from ..resilience.faults import inject as _inject_fault
from ..resilience.heartbeat import LoopLiveness
from ..utils import get_logger

logger = get_logger("serving.multihost")

# Directive channel port (the jax.distributed coordinator uses 8476; the
# deploy renderer exposes both on the headless Service).
CONTROL_PORT = 8477


def _encode(adds, aborts, stop=False, hb=False) -> bytes:
    if hb:
        return b'{"hb": 1}\n'
    payload = {
        "adds": [(rid, ids, dataclasses.asdict(params))
                 for rid, ids, params in adds],
        "aborts": list(aborts),
    }
    if stop:
        payload["stop"] = True
    return (json.dumps(payload) + "\n").encode()


class DirectiveLeader:
    """Rank 0's side: persistent connections to every follower, one
    broadcast per engine-loop iteration. Connections are made lazily with
    retries — followers bind their listener during process startup, which
    may complete after the leader's first request arrives. Once connected, a
    daemon thread heartbeats the channel so idle followers can tell a quiet
    leader from a dead one; a heartbeat send failure is surfaced on the next
    ``broadcast`` (the serving loop's group-abort path)."""

    def __init__(self, addrs: list[str], connect_timeout_s: float = 60.0,
                 heartbeat_interval_s: float = 2.0):
        self.addrs = addrs
        self.timeout = connect_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self._socks: Optional[list[socket.socket]] = None
        # One lock over all sends: broadcast (engine worker thread) and
        # heartbeats (hb thread) must never interleave partial NDJSON frames.
        self._lock = threading.Lock()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_error: Optional[Exception] = None

    def _connect(self) -> list[socket.socket]:
        socks = []
        for addr in self.addrs:
            host, _, port = addr.rpartition(":")
            deadline = time.monotonic() + self.timeout
            while True:
                try:
                    s = socket.create_connection((host, int(port)), timeout=5)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    socks.append(s)
                    logger.info("directive channel up: %s", addr)
                    break
                except OSError as e:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"follower {addr} unreachable: {e}") from e
                    time.sleep(0.5)
        return socks

    def _heartbeat_loop(self) -> None:
        line = _encode([], [], hb=True)
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            with self._lock:
                if self._socks is None:
                    continue
                try:
                    for s in self._socks:
                        s.sendall(line)
                except OSError as e:
                    # Remember and keep quiet: the next broadcast raises it
                    # on the serving thread, which owns group-abort.
                    self._hb_error = e
                    logger.warning("heartbeat send failed (follower dead?): "
                                   "%s", e)

    def broadcast(self, adds, aborts) -> None:
        if _inject_fault("broadcast_fail"):
            raise ConnectionError("KGCT_FAULT broadcast_fail")
        if self._hb_error is not None:
            err, self._hb_error = self._hb_error, None
            raise ConnectionError(
                f"directive channel lost (heartbeat): {err}") from err
        with self._lock:
            if self._socks is None:
                self._socks = self._connect()
            line = _encode(adds, aborts)
            for s in self._socks:
                s.sendall(line)
        if (self._hb_thread is None and self.heartbeat_interval_s > 0):
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="kgct-directive-heartbeat")
            self._hb_thread.start()

    def close(self) -> None:
        self._hb_stop.set()
        with self._lock:
            if self._socks is None:
                return
            for s in self._socks:
                try:
                    s.sendall(_encode([], [], stop=True))
                    s.close()
                except OSError:
                    pass
            self._socks = None


class DirectiveFollower:
    """Rank > 0's side: apply each directive and take exactly one step when
    the leader does. ``bind()`` early (before jax.distributed blocks on the
    process group) so the leader's lazy connect finds the listener."""

    def __init__(self, port: int = CONTROL_PORT, host: str = "0.0.0.0"):
        self._srv = socket.create_server((host, port))

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def run(self, engine: LLMEngine,
            liveness: Optional[LoopLiveness] = None,
            liveness_timeout_s: Optional[float] = None) -> None:
        conn, peer = self._srv.accept()
        logger.info("leader connected from %s", peer)
        # The silence deadline is armed only after the FIRST line arrives:
        # the leader connects followers serially (up to connect_timeout_s
        # EACH) and broadcasts only once every rank is up, so an early-
        # accepted follower may legitimately hear nothing for minutes during
        # staggered startup. Once directives/heartbeats flow, silence past
        # liveness_timeout_s declares the leader dead — without that, a
        # crashed rank 0 leaves the follower in recv() forever with
        # in-flight pages held.
        first_line_seen = False
        buf = b""
        with conn:
            while True:
                while b"\n" not in buf:
                    try:
                        data = conn.recv(1 << 16)
                    except socket.timeout:
                        logger.error(
                            "leader silent for %.1fs (no directives or "
                            "heartbeats): declaring leader dead, "
                            "group-aborting", liveness_timeout_s)
                        n = group_abort(engine)
                        if liveness is not None:
                            liveness.mark_dead(
                                "leader heartbeat lost; "
                                f"{n} requests group-aborted")
                        return
                    if not data:
                        logger.warning("leader connection closed; "
                                       "group-aborting and exiting")
                        n = group_abort(engine)
                        if liveness is not None and n:
                            liveness.mark_dead(
                                f"leader gone mid-flight; {n} requests "
                                "group-aborted")
                        return
                    buf += data
                line, _, buf = buf.partition(b"\n")
                if not first_line_seen:
                    first_line_seen = True
                    if liveness_timeout_s:
                        conn.settimeout(liveness_timeout_s)
                d = json.loads(line)
                if liveness is not None:
                    liveness.beat()
                if d.get("hb"):
                    continue    # liveness only; no step mirrors no work
                if d.get("stop"):
                    logger.info("stop directive; follower exiting")
                    return
                for rid in d["aborts"]:
                    engine.abort_request(rid)
                for rid, ids, params in d["adds"]:
                    try:
                        engine.add_request(rid, ids,
                                           SamplingParams(**params))
                    except ValueError as e:
                        # The leader rejected the same request the same way
                        # (identical config) and did not schedule it.
                        logger.info("request %s rejected in lockstep: %s",
                                    rid, e)
                # Mirror the leader loop exactly: one step iff there is work.
                if engine.has_unfinished_requests():
                    engine.step()
                    if liveness is not None:
                        # A completed step is proof of life — the beat on
                        # line receipt is minutes stale after a first-use
                        # XLA compile inside step().
                        liveness.beat()


def group_abort(engine: LLMEngine) -> int:
    """Cleanly abort every queued/running request on this rank and drain the
    in-flight window so its deferred page releases happen — the rank exits
    (or detaches) with no leaked device state, and waiters see explicit
    aborts instead of a silent hang. Returns the number of aborted
    requests."""
    # Swapped sequences included: left behind they would be restored by the
    # drain loop's schedule calls and keep generating on a dead group.
    # getattr: follower protocol tests drive this with duck-typed engines
    # that predate the two-tier cache.
    seqs = (list(engine.scheduler.waiting) + list(engine.scheduler.running)
            + list(getattr(engine.scheduler, "swapped", ())))
    # Black-box dump BEFORE the abort flood: the flight recorder's ring
    # still holds the directives/steps that led to the group failure, and
    # the rank is about to exit or restart. getattr keeps duck-typed test
    # engines working.
    obs = getattr(engine, "obs", None)
    flight = getattr(obs, "flight", None)
    if flight is not None:
        flight.dump("group_abort", requests=len(seqs))
    for seq in seqs:
        try:
            engine.abort_request(seq.request_id)
        except Exception:
            logger.exception("group-abort: abort_request(%s) failed",
                             seq.request_id)
    # Everything is aborted, so remaining steps only drain the speculative
    # in-flight window (deferred KV page releases), no new device work.
    try:
        while engine.has_unfinished_requests():
            engine.step()
    except Exception:
        logger.exception("group-abort: drain step failed (pages may leak "
                         "until restart)")
    if seqs:
        logger.warning("group-aborted %d in-flight requests", len(seqs))
    return len(seqs)


def serve_follower_health(port: int, host: str = "0.0.0.0",
                          liveness: Optional[LoopLiveness] = None):
    """Minimal /health endpoint on the engine port for rank > 0 pods: the
    StatefulSet's pod template (shared by all ranks) carries httpGet
    readiness/liveness probes, and a follower with no listener would be
    killed by kubelet ~3 min after start, crash-looping the whole process
    group. Runs on a daemon thread; everything but /health is 404.

    With ``liveness``, the 200 is TIED TO ACTUAL LOOP LIVENESS (beaten by
    directives/heartbeats in ``DirectiveFollower.run``): a dead or silent
    loop turns the probe 503 so kubelet restarts the rank instead of keeping
    a zombie alive. Returns the HTTP server (tests read its bound port)."""
    import http.server
    import threading

    class Health(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            if self.path != "/health":
                self.send_response(404)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(b"{}")
                return
            alive = liveness.alive() if liveness is not None else True
            self.send_response(200 if alive else 503)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            if alive:
                self.wfile.write(b'{"status": "follower"}')
            else:
                reason = liveness.reason.replace('"', "'")
                self.wfile.write(
                    json.dumps({"status": "follower loop dead",
                                "reason": reason}).encode())

        def log_message(self, *a):  # quiet
            pass

    srv = http.server.ThreadingHTTPServer((host, port), Health)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="kgct-follower-health").start()
    return srv


def follower_addrs_from_env() -> list[str]:
    """The follower directive endpoints for rank 0.

    KGCT_FOLLOWER_ADDRS (comma-separated host:port) when set — tests and
    custom topologies; otherwise derived from the StatefulSet DNS pattern in
    KGCT_COORDINATOR (…-0.<svc>:<port> -> …-{k}.<svc>:CONTROL_PORT) for
    k in 1..KGCT_NUM_PROCESSES-1, matching deploy/render.py's layout."""
    import os

    explicit = os.environ.get("KGCT_FOLLOWER_ADDRS")
    if explicit:
        return [a for a in explicit.split(",") if a]
    coord = os.environ.get("KGCT_COORDINATOR", "")
    n = int(os.environ.get("KGCT_NUM_PROCESSES", "1"))
    if n <= 1:
        return []
    if "-0." not in coord:
        # Broadcasting to nobody would hang the whole group silently at the
        # first collective — refuse the misconfiguration instead.
        raise RuntimeError(
            f"cannot derive follower addresses: KGCT_COORDINATOR={coord!r} "
            "does not follow the StatefulSet '<name>-0.<svc>:<port>' "
            "pattern; set KGCT_FOLLOWER_ADDRS explicitly")
    host = coord.rpartition(":")[0]
    # Followers bind KGCT_CONTROL_PORT when set; a StatefulSet template
    # shares env across ranks, so derive dial addresses from the same
    # override or the leader would dial the default port forever.
    port = int(os.environ.get("KGCT_CONTROL_PORT", CONTROL_PORT))
    return [f"{host.replace('-0.', f'-{k}.', 1)}:{port}"
            for k in range(1, n)]
