"""Multihost SPMD serving: step-directive replication from rank 0.

Under a mesh that spans processes (the rendered StatefulSet: one engine pod
per host, jax.distributed over ICI/DCN), every rank must run the SAME
engine-step sequence — the jitted step enters collectives, and a rank that
steps alone hangs the process group. But only rank 0 receives client
traffic (the Service pins to pod-index 0, deploy/render.py). The reference
solved this with Ray: the vLLM driver shipped work to workers
(old_README.md:1615-1625). TPU-native replacement:

- The engine's host-side scheduler is DETERMINISTIC given the sequence of
  (admissions, aborts) applied at each step boundary, so lockstep needs
  only that event stream — not tensors, not tokens.
- Rank 0 (leader) broadcasts one DIRECTIVE per worker-loop iteration —
  ``{"adds": [(rid, token_ids, sampling_params)], "aborts": [rid]}`` as one
  NDJSON line over a persistent TCP connection to every follower — BEFORE
  taking its own step, then steps; device collectives do the actual
  synchronization (a lagging follower simply makes the leader's collective
  wait).
- Followers (rank > 0) run no HTTP server: they accept the leader's
  connection, and for each directive apply the events and take exactly one
  engine step. Same config + same seed => identical scheduling, identical
  step programs, lockstep collectives.

Failure model: a dead follower breaks the jax.distributed process group
anyway (collectives hang), so directive-connection errors are fatal — the
StatefulSet restarts the group, matching the reference's reset-first
recovery story (SURVEY §5.3).
"""

from __future__ import annotations

import dataclasses
import json
import socket
import time
from typing import Optional

from ..engine import LLMEngine, SamplingParams
from ..utils import get_logger

logger = get_logger("serving.multihost")

# Directive channel port (the jax.distributed coordinator uses 8476; the
# deploy renderer exposes both on the headless Service).
CONTROL_PORT = 8477


def _encode(adds, aborts, stop=False) -> bytes:
    payload = {
        "adds": [(rid, ids, dataclasses.asdict(params))
                 for rid, ids, params in adds],
        "aborts": list(aborts),
    }
    if stop:
        payload["stop"] = True
    return (json.dumps(payload) + "\n").encode()


class DirectiveLeader:
    """Rank 0's side: persistent connections to every follower, one
    broadcast per engine-loop iteration. Connections are made lazily with
    retries — followers bind their listener during process startup, which
    may complete after the leader's first request arrives."""

    def __init__(self, addrs: list[str], connect_timeout_s: float = 60.0):
        self.addrs = addrs
        self.timeout = connect_timeout_s
        self._socks: Optional[list[socket.socket]] = None

    def _connect(self) -> list[socket.socket]:
        socks = []
        for addr in self.addrs:
            host, _, port = addr.rpartition(":")
            deadline = time.monotonic() + self.timeout
            while True:
                try:
                    s = socket.create_connection((host, int(port)), timeout=5)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    socks.append(s)
                    logger.info("directive channel up: %s", addr)
                    break
                except OSError as e:
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"follower {addr} unreachable: {e}") from e
                    time.sleep(0.5)
        return socks

    def broadcast(self, adds, aborts) -> None:
        if self._socks is None:
            self._socks = self._connect()
        line = _encode(adds, aborts)
        for s in self._socks:
            s.sendall(line)

    def close(self) -> None:
        if self._socks is None:
            return
        for s in self._socks:
            try:
                s.sendall(_encode([], [], stop=True))
                s.close()
            except OSError:
                pass
        self._socks = None


class DirectiveFollower:
    """Rank > 0's side: apply each directive and take exactly one step when
    the leader does. ``bind()`` early (before jax.distributed blocks on the
    process group) so the leader's lazy connect finds the listener."""

    def __init__(self, port: int = CONTROL_PORT, host: str = "0.0.0.0"):
        self._srv = socket.create_server((host, port))

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def run(self, engine: LLMEngine) -> None:
        conn, peer = self._srv.accept()
        logger.info("leader connected from %s", peer)
        buf = b""
        with conn:
            while True:
                while b"\n" not in buf:
                    data = conn.recv(1 << 16)
                    if not data:
                        logger.warning("leader connection closed; exiting")
                        return
                    buf += data
                line, _, buf = buf.partition(b"\n")
                d = json.loads(line)
                if d.get("stop"):
                    logger.info("stop directive; follower exiting")
                    return
                for rid in d["aborts"]:
                    engine.abort_request(rid)
                for rid, ids, params in d["adds"]:
                    try:
                        engine.add_request(rid, ids,
                                           SamplingParams(**params))
                    except ValueError as e:
                        # The leader rejected the same request the same way
                        # (identical config) and did not schedule it.
                        logger.info("request %s rejected in lockstep: %s",
                                    rid, e)
                # Mirror the leader loop exactly: one step iff there is work.
                if engine.has_unfinished_requests():
                    engine.step()


def serve_follower_health(port: int, host: str = "0.0.0.0") -> None:
    """Minimal /health endpoint on the engine port for rank > 0 pods: the
    StatefulSet's pod template (shared by all ranks) carries httpGet
    readiness/liveness probes, and a follower with no listener would be
    killed by kubelet ~3 min after start, crash-looping the whole process
    group. Runs on a daemon thread; everything but /health is 404."""
    import http.server
    import threading

    class Health(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            ok = self.path == "/health"
            self.send_response(200 if ok else 404)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b'{"status": "follower"}' if ok else b"{}")

        def log_message(self, *a):  # quiet
            pass

    srv = http.server.ThreadingHTTPServer((host, port), Health)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="kgct-follower-health").start()


def follower_addrs_from_env() -> list[str]:
    """The follower directive endpoints for rank 0.

    KGCT_FOLLOWER_ADDRS (comma-separated host:port) when set — tests and
    custom topologies; otherwise derived from the StatefulSet DNS pattern in
    KGCT_COORDINATOR (…-0.<svc>:<port> -> …-{k}.<svc>:CONTROL_PORT) for
    k in 1..KGCT_NUM_PROCESSES-1, matching deploy/render.py's layout."""
    import os

    explicit = os.environ.get("KGCT_FOLLOWER_ADDRS")
    if explicit:
        return [a for a in explicit.split(",") if a]
    coord = os.environ.get("KGCT_COORDINATOR", "")
    n = int(os.environ.get("KGCT_NUM_PROCESSES", "1"))
    if n <= 1:
        return []
    if "-0." not in coord:
        # Broadcasting to nobody would hang the whole group silently at the
        # first collective — refuse the misconfiguration instead.
        raise RuntimeError(
            f"cannot derive follower addresses: KGCT_COORDINATOR={coord!r} "
            "does not follow the StatefulSet '<name>-0.<svc>:<port>' "
            "pattern; set KGCT_FOLLOWER_ADDRS explicitly")
    host = coord.rpartition(":")[0]
    # Followers bind KGCT_CONTROL_PORT when set; a StatefulSet template
    # shares env across ranks, so derive dial addresses from the same
    # override or the leader would dial the default port forever.
    port = int(os.environ.get("KGCT_CONTROL_PORT", CONTROL_PORT))
    return [f"{host.replace('-0.', f'-{k}.', 1)}:{port}"
            for k in range(1, n)]
