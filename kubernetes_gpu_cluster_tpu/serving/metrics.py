"""Prometheus-format serving metrics (/metrics endpoint).

The reference had NO metrics surface at all — observability was kubectl
transcripts (SURVEY §5 "Metrics/logging/observability: no Prometheus/
Grafana") — so this is framework-over-reference functionality the north star
asks for: tok/s, TTFT under continuous batching, preemptions, KV page
occupancy.

Counters come from engine.EngineStats (filled inside the step loop) and
scheduler/allocator state; latency distributions are REAL histograms
(``_bucket``/``_sum``/``_count`` with outcome labels, rendered by the
engine's Observability) so Prometheus can compute any quantile across
replicas — the two-point host-side summaries this module used to emit
could not aggregate. Text format per the exposition spec, scrapeable
without any client library; nan-free by construction even on a freshly
started server.
"""

from __future__ import annotations

import time

from ..engine.engine import device_memory_stats


class Metrics:
    def __init__(self, engine):
        self.engine = engine               # LLMEngine
        self.requests_total = 0
        self.responses_total = 0
        self.response_tokens_total = 0
        self._started = time.monotonic()

    # -- hooks called by the API layer --------------------------------------

    def on_request(self) -> None:
        self.requests_total += 1

    def on_finish(self, n_tokens: int) -> None:
        """HTTP-layer completion: counts responses actually delivered to
        clients (engine-side requests_finished also covers aborts/terminated
        sequences, so the two legitimately differ under churn)."""
        self.responses_total += 1
        self.response_tokens_total += n_tokens

    # -- rendering ----------------------------------------------------------

    def render(self) -> str:
        eng = self.engine
        stats = eng.stats
        sched = eng.scheduler
        alloc = sched.allocator
        lines = [
            "# TYPE kgct_requests_total counter",
            f"kgct_requests_total {self.requests_total}",
            "# TYPE kgct_responses_total counter",
            f"kgct_responses_total {self.responses_total}",
            "# TYPE kgct_response_tokens_total counter",
            f"kgct_response_tokens_total {self.response_tokens_total}",
            "# TYPE kgct_requests_finished_total counter",
            f"kgct_requests_finished_total {stats.requests_finished}",
            "# TYPE kgct_tokens_generated_total counter",
            f"kgct_tokens_generated_total {stats.tokens_generated}",
            "# TYPE kgct_prefill_tokens_total counter",
            f"kgct_prefill_tokens_total {stats.prefill_tokens}",
            "# TYPE kgct_engine_steps_total counter",
            f"kgct_engine_steps_total {stats.steps}",
            # Split by kind (ROADMAP item 2): "swap" preemptions park KV in
            # host DRAM and resume via memcpy, "recompute" ones burn a full
            # re-prefill — the ratio is the two-tier cache's value signal.
            "# TYPE kgct_preemptions_total counter",
            'kgct_preemptions_total{kind="recompute"} %d'
            % sched.num_preemptions_by_kind["recompute"],
            'kgct_preemptions_total{kind="swap"} %d'
            % sched.num_preemptions_by_kind["swap"],
            "# TYPE kgct_num_waiting gauge",
            f"kgct_num_waiting {len(sched.waiting)}",
            "# TYPE kgct_num_running gauge",
            f"kgct_num_running {len(sched.running)}",
            "# TYPE kgct_num_swapped gauge",
            f"kgct_num_swapped {len(sched.swapped)}",
            "# TYPE kgct_kv_pages_total gauge",
            f"kgct_kv_pages_total {alloc.num_pages}",
            "# TYPE kgct_kv_pages_free gauge",
            f"kgct_kv_pages_free {alloc.num_free}",
            "# TYPE kgct_uptime_seconds gauge",
            f"kgct_uptime_seconds {time.monotonic() - self._started:.1f}",
        ]
        # Prefix-cache reuse (engine/kv_cache.PrefixCache counts lookups;
        # nothing scraped them until now). Emitted unconditionally — zeros
        # when caching is off or nothing was looked up yet — so a fresh
        # scrape is nan-free and dashboards need no existence check.
        pc = sched.prefix_cache
        hits = pc.hits if pc is not None else 0
        misses = pc.misses if pc is not None else 0
        looked = hits + misses
        lines += [
            "# TYPE kgct_prefix_cache_hit_ratio gauge",
            f"kgct_prefix_cache_hit_ratio {hits / looked if looked else 0.0}",
            "# TYPE kgct_prefix_cache_hits_total counter",
            f"kgct_prefix_cache_hits_total {hits}",
            "# TYPE kgct_prefix_cache_misses_total counter",
            f"kgct_prefix_cache_misses_total {misses}",
            # Second-chance restores of host-spilled prefix pages.
            "# TYPE kgct_prefix_cache_host_hits_total counter",
            "kgct_prefix_cache_host_hits_total %d"
            % (pc.host_hits if pc is not None else 0),
        ]
        # Host KV tier occupancy (two-tier cache). Zeros when swap is off —
        # a fresh scrape stays nan-free and dashboards need no existence
        # check, same contract as the prefix-cache series above.
        swapper = getattr(eng, "swapper", None)
        host_total = swapper.host.num_pages if swapper is not None else 0
        host_used = swapper.host.num_in_use if swapper is not None else 0
        lines += [
            "# TYPE kgct_kv_host_pages_total gauge",
            f"kgct_kv_host_pages_total {host_total}",
            "# TYPE kgct_kv_host_pages_in_use gauge",
            f"kgct_kv_host_pages_in_use {host_used}",
        ]
        # Device telemetry (ROADMAP 4(b) autoscaler inputs): HBM occupancy
        # straight from the jax runtime's allocator counters (0/0 on CPU —
        # nan-free), and the jit-cache entry count across every step program
        # (the tier-1 compile guard's number; flat in steady state, growth
        # under constant traffic = recompilation storm). The jit series is
        # a GAUGE despite the _total spelling: it reads the live cache, so
        # jax.clear_caches()/engine rebuild can shrink it — a counter TYPE
        # would make rate() report a phantom compile storm on any reset.
        hbm_limit, hbm_in_use = device_memory_stats()
        lines += [
            "# TYPE kgct_hbm_bytes_limit gauge",
            f"kgct_hbm_bytes_limit {hbm_limit}",
            "# TYPE kgct_hbm_bytes_in_use gauge",
            f"kgct_hbm_bytes_in_use {hbm_in_use}",
            "# TYPE kgct_jit_compiles_total gauge",
            f"kgct_jit_compiles_total {eng.compiled_step_variants()}",
        ]
        # Histograms (TTFT/TPOT/queue-wait/prefill/step/batch-size/e2e),
        # per-phase step-time counters, and the sampled-decode-ratio gauge —
        # all owned by the engine's Observability.
        lines.extend(eng.obs.render_prometheus())
        return "\n".join(lines) + "\n"
