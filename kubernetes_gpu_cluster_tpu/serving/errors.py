"""Shared OpenAI-shaped error envelopes for the serving stack.

One definition for engine shed/drain (api_server) and router-level
rejections: the router's docstring promises clients parse the SAME envelope
from both layers, so the shape lives in one place instead of drifting
between two copies.

Also home of the ``x-kgct-request-id`` wire contract (the fleet tracing
correlation id): the router mints one per request (honoring an inbound
header), forwards it to the replica, and echoes it on EVERY response —
success or error — so a 429/503 in a client log joins the router span
stream, the replica's engine trace, and the JSON log records on one id.
Defined here because both the router (jax-free process) and the api_server
import this module already.
"""

from __future__ import annotations

import re
from typing import Optional

from aiohttp import web

REQUEST_ID_HEADER = "x-kgct-request-id"

# Disaggregated prefill/decode: the router names the prefill-pool replica a
# decode replica should pull prefilled KV from (serving/handoff.py). Set by
# the ROUTER only — the proxy strips any client-supplied value. Traffic
# that reaches a replica pod DIRECTLY (per-pod DNS) bypasses that strip,
# so the replica enforces its own boundary: with ``--prefill-pool`` set
# (the renderer wires it from prefillReplicas), a header naming any other
# url is never fetched — the request degrades to local recompute.
PREFILL_URL_HEADER = "x-kgct-prefill-url"

# Session survivability: the router names the healthy peer a draining
# replica should PUSH each running sequence's KV to (live migration on
# SIGTERM) — the ring successor of the serving replica, so the router's
# own mid-stream failover re-dispatch finds the parked state where it
# lands. Router-set like the prefill url (client values stripped at the
# proxy; ``--peer-pool`` is the direct-to-pod allowlist).
MIGRATE_URL_HEADER = "x-kgct-migrate-url"

# Fleet-wide prefix cache: the router names the ring OWNER of this
# request's affinity key when the pick had to land elsewhere (owner
# over-bound or out of rotation) — the chosen replica pulls the owner's
# cached prefix KV instead of recomputing it (``POST
# /internal/fetch_prefix``, serving/fleet_cache.py). Router-set like the
# prefill url (client values stripped at the proxy); ``--peer-pool`` is
# the direct-to-pod allowlist, and the replica-side roofline gate skips
# pulls priced above a local recompute.
PREFIX_SOURCE_HEADER = "x-kgct-prefix-source"

# Multi-tenant QoS: the request's priority class. Resolution order (one
# definition, engine/qos.resolve_tier_name, shared by router and replica):
# a valid inbound header naming a CONFIGURED tier wins; else the
# ``session_id``/``user`` tenant key is looked up against the tiers' user
# pins; else the default tier. The router propagates the tier it resolved
# upstream in this header so both layers attribute the request
# identically; a header naming an unconfigured tier is a 400 at the
# replica (loud, not silently re-classed). Ignored when no tiers are
# configured (QoS off is byte-identical to today).
QOS_TIER_HEADER = "x-kgct-qos-tier"

# Echoed by ``POST /internal/resume``: how the resumed stream was
# reconstructed — "import" (parked migrated KV scattered in, decode
# resumes directly) or "recompute" (token-replay re-prefill). The router
# attributes kgct_failovers_total{outcome=} from it.
RESUME_MODE_HEADER = "x-kgct-resume-mode"


class StreamMigratedError(Exception):
    """Posted into a live stream's output queue when its sequence was
    live-migrated to a peer (drain): the handler aborts the client
    connection WITHOUT a terminal SSE frame, so the router's relay sees an
    incomplete stream and re-dispatches to the migration target. Carries
    the peer url for logs/traces."""

    def __init__(self, peer_url: str):
        super().__init__(f"stream migrated to {peer_url}")
        self.peer_url = peer_url

# Ids must be safe to echo into headers, log records, and trace JSON: a
# bounded charset, no whitespace/control bytes, bounded length. Anything
# else is treated as absent and a fresh id is minted.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:+-]{0,127}$")


def valid_request_id(rid: Optional[str]) -> Optional[str]:
    """``rid`` when it satisfies the header contract, else None."""
    if rid and _REQUEST_ID_RE.match(rid):
        return rid
    return None


def overloaded_error(status: int, message: str,
                     retry_after_s: float) -> web.Response:
    """Shed/drain/no-capacity rejection: OpenAI-shaped error body plus a
    Retry-After header so well-behaved clients (and bench.py's overload
    phase) back off for the time the backlog actually needs instead of
    hammering a doomed queue."""
    return web.json_response(
        {"error": {"message": message, "type": "overloaded_error",
                   "code": status}},
        status=status,
        headers={"Retry-After": str(max(int(retry_after_s), 1))})
