"""Shared OpenAI-shaped error envelopes for the serving stack.

One definition for engine shed/drain (api_server) and router-level
rejections: the router's docstring promises clients parse the SAME envelope
from both layers, so the shape lives in one place instead of drifting
between two copies.
"""

from __future__ import annotations

from aiohttp import web


def overloaded_error(status: int, message: str,
                     retry_after_s: float) -> web.Response:
    """Shed/drain/no-capacity rejection: OpenAI-shaped error body plus a
    Retry-After header so well-behaved clients (and bench.py's overload
    phase) back off for the time the backlog actually needs instead of
    hammering a doomed queue."""
    return web.json_response(
        {"error": {"message": message, "type": "overloaded_error",
                   "code": status}},
        status=status,
        headers={"Retry-After": str(max(int(retry_after_s), 1))})
