"""Request-lifecycle tracer: bounded ring of typed events, Perfetto export.

Every request's path through the engine — arrival, queueing, scheduling,
prefill chunks, first token, preemption/resume, finish/abort — is recorded
as a timestamped event in a fixed-size ring buffer. The ring is the whole
memory story: O(capacity) regardless of uptime, oldest events dropped first,
append is one deque.append on the step-loop thread (no locks — CPython's
deque append is atomic, and the exporter snapshots with list()).

Export is Chrome/Perfetto trace-event JSON (``GET /debug/trace``): each
request becomes an async span (``ph: b/n/e`` keyed by request id) on the
"requests" track, and each engine step's phase timings (phases.py) become
complete slices (``ph: X``) on the "engine.step" track — load the file in
https://ui.perfetto.dev and TTFT decomposes visually into queue wait,
prefill, and fetch.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

# Typed event kinds (the request lifecycle, in rough order). "decode",
# "mixed" and "spec" are engine-wide per-step events (empty request id); a
# "mixed" event carries the step's prefill/decode token split, a "spec"
# event the drafted/accepted draft-token counts. "preempt" carries the
# preemption kind (recompute|swap) and "swap" a two-tier KV transfer's
# direction + page count.
EVENT_KINDS = ("arrival", "queued", "scheduled", "prefill_chunk",
               "first_token", "decode", "mixed", "spec", "preempt",
               "swap", "resume", "finish", "abort")

# Events that OPEN / CLOSE a request's async span in the Perfetto export.
_OPEN = "arrival"
_CLOSE = ("finish", "abort")


class TraceEvent:
    __slots__ = ("ts", "kind", "request_id", "args")

    def __init__(self, ts: float, kind: str, request_id: str, args: dict):
        self.ts = ts
        self.kind = kind
        self.request_id = request_id
        self.args = args

    def as_dict(self) -> dict:
        return {"ts": self.ts, "kind": self.kind,
                "request_id": self.request_id, **self.args}


class RequestTracer:
    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self.enabled = enabled
        self._ring: deque[TraceEvent] = deque(maxlen=capacity)
        # Engine-wide events (empty request id — one "decode" instant per
        # step window) get their own ring: sustained decode emits hundreds
        # per second and must never evict the request-lifecycle events the
        # TTFT/queue-wait attribution exists to keep.
        self._step_ring: deque[TraceEvent] = deque(maxlen=capacity // 4)

    def emit(self, kind: str, request_id: str = "", **args) -> None:
        if not self.enabled:
            return
        ring = self._ring if request_id else self._step_ring
        ring.append(TraceEvent(time.monotonic(), kind, request_id, args))

    def events(self) -> list[TraceEvent]:
        return sorted([*self._ring, *self._step_ring], key=lambda e: e.ts)

    def clear(self) -> None:
        self._ring.clear()
        self._step_ring.clear()

    # -- export --------------------------------------------------------------

    def export_perfetto(self, step_records: Optional[list] = None) -> dict:
        """Chrome trace-event JSON. ``step_records``: phases.StepPhaseStats
        records to render as engine.step phase slices alongside the request
        spans. Timestamps are µs relative to the earliest event so the trace
        opens at t=0 in the viewer."""
        events = self.events()
        records = list(step_records or [])
        t0_candidates = [e.ts for e in events]
        t0_candidates += [ph[1] for r in records for ph in r["phases"]]
        t0 = min(t0_candidates) if t0_candidates else 0.0

        def us(ts: float) -> float:
            return round((ts - t0) * 1e6, 1)

        trace_events = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "kgct-engine"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "requests"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
             "args": {"name": "engine.step"}},
        ]
        open_ids: set[str] = set()
        for e in events:
            if not e.request_id:
                # Engine-wide event (e.g. per-window "decode"): an instant on
                # the step track.
                trace_events.append(
                    {"name": e.kind, "cat": "engine", "ph": "i", "s": "t",
                     "pid": 1, "tid": 2, "ts": us(e.ts), "args": e.args})
                continue
            common = {"cat": "request", "id": e.request_id, "pid": 1,
                      "tid": 1, "ts": us(e.ts)}
            if e.kind == _OPEN:
                open_ids.add(e.request_id)
                trace_events.append(
                    {"name": e.request_id, "ph": "b", **common,
                     "args": e.args})
            elif e.kind in _CLOSE:
                if e.request_id not in open_ids:
                    # Arrival fell off the ring: synthesize a zero-length
                    # open so the close still pairs (Perfetto drops orphans).
                    trace_events.append(
                        {"name": e.request_id, "ph": "b", **common,
                         "args": {"truncated": True}})
                open_ids.discard(e.request_id)
                trace_events.append(
                    {"name": e.request_id, "ph": "e", **common,
                     "args": {"event": e.kind, **e.args}})
            else:
                trace_events.append(
                    {"name": e.kind, "ph": "n", **common, "args": e.args})
        for rec in records:
            for name, start, dur in rec["phases"]:
                trace_events.append(
                    {"name": name, "cat": "step", "ph": "X", "pid": 1,
                     "tid": 2, "ts": us(start), "dur": round(dur * 1e6, 1),
                     "args": {"step": rec["step"], "kind": rec["kind"],
                              "batch": rec["batch"]}})
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}
