"""Request-lifecycle tracer: bounded ring of typed events, Perfetto export.

Every request's path through the engine — arrival, queueing, scheduling,
prefill chunks, first token, preemption/resume, finish/abort — is recorded
as a timestamped event in a fixed-size ring buffer. The ring is the whole
memory story: O(capacity) regardless of uptime, oldest events dropped first,
append is one deque.append on the step-loop thread (no locks — CPython's
deque append is atomic, and the exporter snapshots with list()).

Export is Chrome/Perfetto trace-event JSON (``GET /debug/trace``): each
request becomes an async span (``ph: b/n/e`` keyed by request id) on the
"requests" track, and each engine step's phase timings (phases.py) become
complete slices (``ph: X``) on the "engine.step" track — load the file in
https://ui.perfetto.dev and TTFT decomposes visually into queue wait,
prefill, and fetch.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Optional

# Typed event kinds (the request lifecycle, in rough order). "decode",
# "mixed" and "spec" are engine-wide per-step events (empty request id); a
# "mixed" event carries the step's prefill/decode token split, a "spec"
# event the drafted/accepted draft-token counts. "preempt" carries the
# preemption kind (recompute|swap) and "swap" a two-tier KV transfer's
# direction + page count, "handoff" a disaggregated KV handoff
# (side=export|import, outcome/bytes/ms). The router's span stream reuses the same
# open/close kinds with its own instants: "pick" (policy + replica + owner
# hit/overflow/remap), "connect_retry" (connect-phase failover), "ttfb"
# (upstream headers latency), "relay" (stream relay complete, bytes).
EVENT_KINDS = ("arrival", "queued", "scheduled", "prefill_chunk",
               "first_token", "decode", "mixed", "spec", "spec_mixed",
               "preempt", "swap", "handoff", "migrate", "resume", "finish",
               "abort", "pick", "connect_retry", "ttfb", "relay", "failover")

# Events that OPEN / CLOSE a request's async span in the Perfetto export.
_OPEN = "arrival"
_CLOSE = ("finish", "abort")


class TraceEvent:
    __slots__ = ("ts", "kind", "request_id", "args")

    def __init__(self, ts: float, kind: str, request_id: str, args: dict):
        self.ts = ts
        self.kind = kind
        self.request_id = request_id
        self.args = args

    def as_dict(self) -> dict:
        return {"ts": self.ts, "kind": self.kind,
                "request_id": self.request_id, **self.args}


class RequestTracer:
    def __init__(self, capacity: int = 8192,
                 enabled: Optional[bool] = None, recorder=None):
        """``enabled`` None resolves the ``KGCT_TRACE`` kill switch here —
        the ONE definition of the toggle, shared by the engine's
        Observability and the router's span stream.

        ``recorder``: an optional flight recorder (flightrecorder.py)
        every emit is MIRRORED into — one extra deque append, so the
        black-box capture rides the same call sites as the trace ring. The
        mirror is independent of ``enabled``: the flight recorder is the
        always-on crash-capture surface and has its own kill switch
        (KGCT_FLIGHT=0)."""
        if enabled is None:
            enabled = os.environ.get("KGCT_TRACE", "1") != "0"
        self.enabled = enabled
        self.recorder = recorder
        self._ring: deque[TraceEvent] = deque(maxlen=capacity)
        # Engine-wide events (empty request id — one "decode" instant per
        # step window) get their own ring: sustained decode emits hundreds
        # per second and must never evict the request-lifecycle events the
        # TTFT/queue-wait attribution exists to keep.
        self._step_ring: deque[TraceEvent] = deque(maxlen=capacity // 4)

    def emit(self, kind: str, request_id: str = "", **args) -> None:
        rec = self.recorder
        if rec is not None:
            rec.record(kind, request_id, args)
        if not self.enabled:
            return
        ring = self._ring if request_id else self._step_ring
        ring.append(TraceEvent(time.monotonic(), kind, request_id, args))

    def events(self) -> list[TraceEvent]:
        return sorted([*self._ring, *self._step_ring], key=lambda e: e.ts)

    def clear(self) -> None:
        self._ring.clear()
        self._step_ring.clear()

    # -- export --------------------------------------------------------------

    def export_perfetto(self, step_records: Optional[list] = None,
                        process_name: str = "kgct-engine") -> dict:
        """Chrome trace-event JSON. ``step_records``: phases.StepPhaseStats
        records to render as engine.step phase slices alongside the request
        spans. Timestamps are µs relative to the earliest event so the trace
        opens at t=0 in the viewer; the top-level ``kgctT0Unix`` key (wall
        clock of that origin, None when the trace is empty) lets
        :func:`merge_perfetto` re-base several processes' exports onto one
        timeline. Viewers ignore the extra key."""
        events = self.events()
        records = list(step_records or [])
        t0_candidates = [e.ts for e in events]
        t0_candidates += [ph[1] for r in records for ph in r["phases"]]
        t0 = min(t0_candidates) if t0_candidates else 0.0
        t0_unix = (time.time() - (time.monotonic() - t0)
                   if t0_candidates else None)

        def us(ts: float) -> float:
            return round((ts - t0) * 1e6, 1)

        trace_events = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": process_name}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "requests"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
             "args": {"name": "engine.step"}},
        ]
        open_ids: set[str] = set()
        for e in events:
            if not e.request_id:
                # Engine-wide event (e.g. per-window "decode"): an instant on
                # the step track.
                trace_events.append(
                    {"name": e.kind, "cat": "engine", "ph": "i", "s": "t",
                     "pid": 1, "tid": 2, "ts": us(e.ts), "args": e.args})
                continue
            common = {"cat": "request", "id": e.request_id, "pid": 1,
                      "tid": 1, "ts": us(e.ts)}
            if e.kind == _OPEN:
                open_ids.add(e.request_id)
                trace_events.append(
                    {"name": e.request_id, "ph": "b", **common,
                     "args": e.args})
            elif e.kind in _CLOSE:
                if e.request_id not in open_ids:
                    # Arrival fell off the ring: synthesize a zero-length
                    # open so the close still pairs (Perfetto drops orphans).
                    trace_events.append(
                        {"name": e.request_id, "ph": "b", **common,
                         "args": {"truncated": True}})
                open_ids.discard(e.request_id)
                trace_events.append(
                    {"name": e.request_id, "ph": "e", **common,
                     "args": {"event": e.kind, **e.args}})
            else:
                trace_events.append(
                    {"name": e.kind, "ph": "n", **common, "args": e.args})
        for rec in records:
            for name, start, dur in rec["phases"]:
                trace_events.append(
                    {"name": name, "cat": "step", "ph": "X", "pid": 1,
                     "tid": 2, "ts": us(start), "dur": round(dur * 1e6, 1),
                     "args": {"step": rec["step"], "kind": rec["kind"],
                              "batch": rec["batch"]}})
        return {"traceEvents": trace_events, "displayTimeUnit": "ms",
                "kgctT0Unix": t0_unix}


def merge_perfetto(docs: list) -> dict:
    """Merge several processes' ``export_perfetto`` documents into ONE
    Perfetto timeline with per-process tracks.

    ``docs``: [(process_label, doc), ...] — the first entry is conventionally
    the router, the rest its replicas. Each doc's events are re-based from
    its own t=0 onto the earliest process's origin using the ``kgctT0Unix``
    anchors (events stay untouched when an anchor is missing — an empty
    trace has nothing to shift), and re-pid'd 1..N so every process renders
    as its own track group. Request spans keep their ids, so a request that
    crossed router -> replica -> engine shows as correlated spans across
    tracks.

    Anchors are wall clock: across PODS the merge is only as aligned as the
    nodes' clocks (NTP-level skew, typically ms) — good enough to eyeball a
    request's path, not for sub-ms cross-host timing."""
    anchors = [d.get("kgctT0Unix") for _, d in docs]
    known = [a for a in anchors if a is not None]
    g0 = min(known) if known else None
    out_events: list = []
    for pid, (label, doc) in enumerate(docs, start=1):
        anchor = doc.get("kgctT0Unix")
        shift_us = (round((anchor - g0) * 1e6, 1)
                    if anchor is not None and g0 is not None else 0.0)
        for e in doc.get("traceEvents", []):
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") == "M":
                if e.get("name") == "process_name":
                    e["args"] = {"name": label}
            elif "ts" in e:
                e["ts"] = round(e["ts"] + shift_us, 1)
            out_events.append(e)
    return {"traceEvents": out_events, "displayTimeUnit": "ms",
            "kgctT0Unix": g0}
