"""Step-phase attribution: where each engine step's wall time goes.

``LLMEngine.step()`` decomposes into named phases — schedule (host-side
batch assembly policy), host_prep (numpy packing + host->device upload),
device_dispatch (jit call; async dispatch, near-zero unless compiling),
device_fetch (the blocking device->host sync), postproc (stop checks,
output assembly), and detokenize (recorded by the HTTP layer, which owns
the tokenizer). A TTFT or tok/s regression then decomposes into a phase
delta instead of a guess — the attribution VERDICT r5 said was impossible
("no way to tell whether the time is queue wait, chunked-prefill stalls,
device step time, or host-side detokenize").

Cost per phase is two perf-counter reads and a list append; per step a dict
merge into running totals — amortized nanoseconds against multi-ms steps,
which is what keeps the tracer's decode-path overhead within the <=1% tok/s
budget.
"""

from __future__ import annotations

import time
from collections import deque

PHASES = ("schedule", "host_prep", "device_dispatch", "device_fetch",
          "postproc", "detokenize")


class _PhaseCtx:
    """Reusable context manager: ``with stats.phase("host_prep"):``."""
    __slots__ = ("_stats", "_name", "_t0", "_start")

    def __init__(self, stats: "StepPhaseStats", name: str):
        self._stats = stats
        self._name = name

    def __enter__(self):
        self._start = time.monotonic()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.record(self._name, time.perf_counter() - self._t0,
                           start=self._start)
        return False


class StepPhaseStats:
    def __init__(self, capacity: int = 512):
        self.totals = {p: 0.0 for p in PHASES}
        self.counts = {p: 0 for p in PHASES}
        self.steps_recorded = 0
        # Per-step records for trace export: {"step", "kind", "batch",
        # "duration_s", "phases": [(name, start_monotonic, dur_s), ...]}
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._current: list = []       # phases of the in-progress step
        self.current_durs: dict[str, float] = {}   # name -> dur, this step
        # Out-of-step slices (the HTTP layer's detokenize) recorded from a
        # thread that is NOT the engine step loop: they must never touch
        # _current/current_durs (the step loop swaps those unsynchronized),
        # so they land in their own ring and merge at export time.
        self._detached: deque = deque(maxlen=256)

    def phase(self, name: str) -> _PhaseCtx:
        return _PhaseCtx(self, name)

    def record(self, name: str, dur: float, start: float = None) -> None:
        """Record one phase occurrence. ``start=None`` marks an out-of-step
        caller (the HTTP layer's detokenize, on the event-loop thread): it
        stamps now-dur and goes to the detached ring only — the step-local
        ``_current``/``current_durs`` belong to the engine thread, which
        concurrently swaps them in start_step/end_step."""
        self.totals[name] = self.totals.get(name, 0.0) + dur
        self.counts[name] = self.counts.get(name, 0) + 1
        if start is None:
            self._detached.append((name, time.monotonic() - dur, dur))
            return
        self._current.append((name, start, dur))
        self.current_durs[name] = self.current_durs.get(name, 0.0) + dur

    def start_step(self) -> None:
        self._current = []
        self.current_durs = {}

    def end_step(self, step: int, kind: str, batch: int,
                 duration_s: float) -> None:
        self.steps_recorded += 1
        self._ring.append({"step": step, "kind": kind, "batch": batch,
                           "duration_s": duration_s,
                           "phases": self._current})
        self._current = []

    def discard_step(self) -> None:
        """An idle step() (no batch, no in-flight window) carries no signal;
        dropping it keeps the totals about real work. The phase durations
        already added to totals stay — they are real time spent (an empty
        schedule() call is still schedule time)."""
        self._current = []

    def step_records(self) -> list[dict]:
        return list(self._ring)

    def detached_records(self) -> list[dict]:
        """Out-of-step slices wrapped in the step-record shape so the trace
        exporter renders them on the engine.step track like any phase."""
        slices = list(self._detached)
        if not slices:
            return []
        return [{"step": -1, "kind": "http", "batch": 0, "phases": slices}]

    def clear_records(self) -> None:
        """Drop the per-step and detached rings (a ``?clear=1`` scoped trace
        capture); cumulative totals/counts — the /metrics contract — stay."""
        self._ring.clear()
        self._detached.clear()

    def breakdown(self) -> dict:
        """Aggregate phase attribution: total seconds and mean ms per
        occurrence for each phase — the dict bench.py folds into its JSON."""
        out = {}
        for p in PHASES:
            n = self.counts.get(p, 0)
            out[p] = {
                "total_s": round(self.totals.get(p, 0.0), 6),
                "count": n,
                "mean_ms": (round(self.totals[p] / n * 1e3, 3) if n else 0.0),
            }
        return out
