"""Black-box flight recorder: always-on crash capture for the serving plane.

When a watchdog trips, a multihost group-abort fires, or a SIGTERM drain
begins, the interesting evidence is the SECONDS THAT PRECEDED the event —
queue depths building, swaps thrashing, a replica's inflight count pinned —
and by the time anyone attaches a debugger that history is gone. The flight
recorder keeps it: a fixed-size ring of recent trace events (mirrored from
the request tracer, one deque append per event) interleaved with periodic
state snapshots (scheduler queue depths, KV pool occupancy on both tiers,
router per-replica inflight), and on a fatal transition the whole ring is
dumped to a JSON file an operator or postmortem pipeline reads after the
pod is restarted.

Hot-path discipline (enforced by the KGCT012 lint rule): ``record`` and
``maybe_snapshot`` are O(append) — no I/O, no serialization, no locks, no
host syncs. The expensive part (``dump``/``export``) runs only on failure
paths and debug endpoints, off the step loop.

Dumps land under ``KGCT_FLIGHT_DIR`` (default ``/tmp/kgct-flight``), one
file per trigger: ``flight-<reason>-<pid>-<ms>.json``. Disable the whole
recorder with ``KGCT_FLIGHT=0`` (record becomes a no-op, dump returns
None); engine outputs are byte-identical either way — the recorder only
observes.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Callable, Optional

from ..utils import get_logger

logger = get_logger("observability.flight")

# Where dump() writes, read at dump time so tests and operators can redirect
# a live process without restart.
FLIGHT_DIR_ENV = "KGCT_FLIGHT_DIR"
DEFAULT_FLIGHT_DIR = "/tmp/kgct-flight"


class FlightRecorder:
    """Fixed-size ring of (ts, kind, request_id, args) tuples.

    ``record`` is the write API the tracer mirrors into (and failure paths
    call directly); ``maybe_snapshot`` appends a state snapshot from the
    registered source at most once per ``snapshot_interval_s`` — callers
    invoke it opportunistically (the engine once per step, the router once
    per health cycle), so an idle process snapshots nothing and a busy one
    pays one monotonic read per call."""

    def __init__(self, capacity: int = 2048,
                 snapshot_interval_s: float = 1.0,
                 enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("KGCT_FLIGHT", "1") != "0"
        self.enabled = enabled
        self.capacity = capacity
        self.snapshot_interval_s = snapshot_interval_s
        self._ring: deque = deque(maxlen=capacity)
        self._snapshot_source: Optional[Callable[[], dict]] = None
        self._last_snapshot = 0.0
        self.dumps_total = 0
        self.last_dump_path: Optional[str] = None

    # -- hot path ------------------------------------------------------------

    def record(self, kind: str, request_id: str = "",
               args: Optional[dict] = None) -> None:
        """One event append. The args dict is stored BY REFERENCE — callers
        must not mutate it afterwards (the tracer builds a fresh dict per
        emit, so the mirror costs nothing extra)."""
        if not self.enabled:
            return
        self._ring.append((time.monotonic(), kind, request_id, args))

    def set_snapshot_source(self, source: Callable[[], dict]) -> None:
        """Register the O(1) state reader (queue depths, pool occupancy)
        snapshots are taken from. Must be non-blocking: attribute reads and
        len() only, never device syncs or I/O."""
        self._snapshot_source = source

    def maybe_snapshot(self) -> None:
        if not self.enabled or self._snapshot_source is None:
            return
        now = time.monotonic()
        if now - self._last_snapshot < self.snapshot_interval_s:
            return
        self._last_snapshot = now
        try:
            snap = self._snapshot_source()
        except Exception:
            return      # a broken source must never take the step loop down
        self._ring.append((now, "snapshot", "", snap))

    # -- export / dump (OFF the hot path) ------------------------------------

    def export(self) -> dict:
        """JSON-ready view of the ring. Timestamps are ``time.monotonic``
        seconds; ``unix_minus_monotonic`` converts them to wall clock
        (unix = ts + unix_minus_monotonic) for cross-process correlation."""
        events = [{"ts": round(ts, 6), "kind": kind,
                   **({"request_id": rid} if rid else {}),
                   **(args or {})}
                  for ts, kind, rid, args in list(self._ring)]
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "snapshot_interval_s": self.snapshot_interval_s,
            "unix_minus_monotonic": time.time() - time.monotonic(),
            "dumps_total": self.dumps_total,
            "events": events,
        }

    def dump(self, reason: str, **info) -> Optional[str]:
        """Write the ring to ``KGCT_FLIGHT_DIR`` with the triggering event
        appended last (so the file is self-describing: the trigger and the
        seconds that preceded it). Best-effort and never raises — dump runs
        on failure paths where a secondary exception would mask the primary
        one. Returns the file path, or None (disabled / write failed)."""
        if not self.enabled:
            return None
        self.record(reason, args=dict(info))
        try:
            flight_dir = os.environ.get(FLIGHT_DIR_ENV, DEFAULT_FLIGHT_DIR)
            os.makedirs(flight_dir, exist_ok=True)
            path = os.path.join(
                flight_dir,
                f"flight-{reason}-{os.getpid()}-{int(time.time() * 1e3)}.json")
            doc = {"reason": reason, "info": dict(info),
                   "dumped_at_unix": time.time(), **self.export()}
            with open(path, "w") as f:
                json.dump(doc, f)
        except Exception:
            logger.exception("flight-recorder dump failed (reason=%s)",
                             reason)
            return None
        self.dumps_total += 1
        self.last_dump_path = path
        logger.warning("flight-recorder dump (%s): %s", reason, path)
        return path
