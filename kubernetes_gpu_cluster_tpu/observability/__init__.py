"""Observability subsystem: request tracing, phase attribution, histograms.

One ``Observability`` object per engine, shared with its scheduler: the step
loop and scheduler call the ``on_*`` lifecycle hooks; serving/metrics.py
renders the histogram state into /metrics; serving/api_server.py exports the
trace ring via /debug/trace; bench.py reads the TTFT decomposition deques.
Everything here is bounded (rings + fixed-bucket histograms) and lock-free
on the hot path — the engine step loop must never block on observability.

Disable entirely with ``KGCT_TRACE=0`` (hooks become cheap early-returns;
histograms still fill — they are the /metrics contract). The black-box
flight recorder (flightrecorder.py) mirrors the same events into its own
always-on ring (kill switch ``KGCT_FLIGHT=0``) and is NOT touched by
``/debug/trace?clear=1`` — a scoped capture must never erase the crash
evidence.
"""

from __future__ import annotations

import time
from collections import deque

from .flightrecorder import FlightRecorder
from .phases import PHASES, StepPhaseStats
from .prometheus import (BATCH_BUCKETS, LATENCY_BUCKETS_S, Histogram, fmt,
                         render_gauge)
from .trace import EVENT_KINDS, RequestTracer, merge_perfetto

__all__ = ["Observability", "Histogram", "RequestTracer", "StepPhaseStats",
           "FlightRecorder", "SLOTracker", "merge_perfetto",
           "EVENT_KINDS", "PHASES", "LATENCY_BUCKETS_S", "BATCH_BUCKETS",
           "render_gauge", "fmt"]

# The attainment bar when no admission-control budget is configured: the
# north-star "p50 TTFT <= 1 s" target. An operator budget
# (ResilienceConfig.default_ttft_budget_ms, wired by the API server)
# overrides it so the SLO gauge and the 429 shed line agree on one number.
SLO_DEFAULT_TTFT_BUDGET_MS = 1000.0


class SLOTracker:
    """Rolling SLO view over recent requests — the autoscaler-facing signal
    (ROADMAP item 4(b)): what fraction of recent traffic met its TTFT
    budget, and how many tokens/s the budget-meeting requests delivered
    (goodput — raw tok/s counts tokens nobody would have waited for).

    Bounded by construction: a fixed-size TTFT window (count-based, the
    last N first tokens) and a time-pruned goodput window. All reads are
    nan-free: attainment over an empty window is 1.0 (nothing has missed
    its budget), goodput is 0.0.

    Thread model: the engine WORKER thread writes (on_first_token /
    on_finish inside the step loop) while the HTTP thread reads
    (/metrics render). Writers are single-threaded and own all mutation
    (including the goodput prune); readers take a ``list()`` snapshot of
    each deque — atomic under the GIL — and never mutate, so a scrape can
    land mid-append without a 'deque mutated during iteration' error or a
    popleft race."""

    def __init__(self, ttft_budget_ms=None, window: int = 256,
                 goodput_window_s: float = 60.0):
        self.ttft_budget_ms = ttft_budget_ms     # None -> default bar
        self.goodput_window_s = goodput_window_s
        self._ttfts: deque = deque(maxlen=window)
        self._good: deque = deque()              # (finish_ts, tokens)
        # Start of the observation span (reset by clear()): a server up
        # 10 s must divide its goodput by 10 s, not the full 60 s window.
        self._window_start = time.monotonic()

    @property
    def budget_ms(self) -> float:
        return (self.ttft_budget_ms if self.ttft_budget_ms is not None
                else SLO_DEFAULT_TTFT_BUDGET_MS)

    def on_first_token(self, ttft_s: float) -> None:
        self._ttfts.append(ttft_s)

    def on_finish(self, ttft_s: float, n_tokens: int) -> None:
        if n_tokens <= 0 or ttft_s * 1e3 > self.budget_ms:
            return
        now = time.monotonic()
        self._good.append((now, n_tokens))
        # Writer-side prune bounds the deque to ~the window's finishes;
        # only this (single) writer thread ever pops.
        cutoff = now - self.goodput_window_s
        good = self._good
        while good and good[0][0] < cutoff:
            good.popleft()

    def attainment(self) -> float:
        """Fraction of the recent TTFT window under the budget; 1.0 on an
        empty window (a fresh server has missed nothing)."""
        ttfts = list(self._ttfts)          # snapshot: reader never iterates live
        if not ttfts:
            return 1.0
        bar = self.budget_ms
        return sum(1 for t in ttfts if t * 1e3 <= bar) / len(ttfts)

    def goodput_tokens_per_sec(self) -> float:
        """Tokens/s delivered by budget-meeting requests over the rolling
        window — 0.0 when idle. The denominator is the OBSERVED span
        (capped at the window): dividing a 10 s-old server's tokens by the
        full 60 s would systematically understate goodput. Read-only: the
        window filter re-applies on the snapshot (entries the writer has
        not pruned yet but that aged out are excluded here too)."""
        now = time.monotonic()
        cutoff = now - self.goodput_window_s
        tokens = sum(n for ts, n in list(self._good) if ts >= cutoff)
        if not tokens:
            return 0.0
        span = min(self.goodput_window_s,
                   max(now - self._window_start, 1e-6))
        return tokens / span

    def clear(self) -> None:
        """Reset the rolling windows (bench phase boundaries); the budget
        stays."""
        self._ttfts.clear()
        self._good.clear()
        self._window_start = time.monotonic()


def _outcome(seq, reason) -> str:
    """finished | aborted | preempted — the label the e2e/TTFT-facing series
    carry. A request that was ever preempted finished late through no fault
    of its own; labeling it lets QoS dashboards split the tail."""
    rv = getattr(reason, "value", reason)
    if rv == "abort":
        return "aborted"
    if rv == "migrated":
        # Live-migrated to a peer (drain): locally terminal, but the client
        # stream continues elsewhere — its tokens WERE delivered, so the
        # goodput gate keeps them; the e2e series splits them out.
        return "migrated"
    if getattr(seq, "preempt_count", 0) > 0:
        return "preempted"
    return "finished"


class Observability:
    def __init__(self, trace_capacity: int = 8192,
                 enabled: bool = None):
        # Black-box flight recorder: mirrors every trace emit into its own
        # bounded ring (plus periodic state snapshots) and dumps to a JSON
        # file on fatal transitions — independent kill switch KGCT_FLIGHT=0.
        self.flight = FlightRecorder()
        # enabled=None: the tracer resolves the KGCT_TRACE kill switch
        # itself (the one definition, shared with the router's tracer).
        self.tracer = RequestTracer(capacity=trace_capacity, enabled=enabled,
                                    recorder=self.flight)
        # Rolling SLO layer: TTFT attainment + goodput, the autoscaler
        # signals. The API server points ttft_budget_ms at the admission
        # controller's budget so both layers grade against one bar.
        self.slo = SLOTracker()
        # Multi-tenant QoS: per-tier SLO trackers + served counters, keyed
        # by the CONFIGURED tier names only (bounded label cardinality,
        # KGCT007 — never raw user ids). Empty when QoS is off: no labeled
        # series render and the scrape is byte-identical to the tier-less
        # server. configure_qos_tiers wires them from engine config.
        self.slo_by_tier: dict[str, SLOTracker] = {}
        self.finished_by_tier: dict[str, int] = {}
        self._qos_default_tier: str = ""
        self.phases = StepPhaseStats()
        self.ttft = Histogram(
            "kgct_ttft_seconds", "time to first token", labels=("outcome",))
        self.tpot = Histogram(
            "kgct_tpot_seconds", "inter-token latency (per-request mean)")
        self.queue_wait = Histogram(
            "kgct_queue_wait_seconds", "arrival to first scheduling")
        self.prefill_latency = Histogram(
            "kgct_prefill_seconds", "scheduling to first token, minus fetch")
        self.step_duration = Histogram(
            "kgct_step_seconds", "engine step wall time")
        self.batch_size = Histogram(
            "kgct_batch_size_per_step", "real sequences per engine step",
            buckets=BATCH_BUCKETS)
        self.e2e_latency = Histogram(
            "kgct_request_e2e_seconds", "arrival to finish",
            labels=("outcome",))
        # TTFT decomposition samples for bench.py (queue wait / prefill
        # compute / first-window device->host fetch).
        self.ttft_queue_s: deque = deque(maxlen=1024)
        self.ttft_prefill_s: deque = deque(maxlen=1024)
        self.ttft_fetch_s: deque = deque(maxlen=1024)
        # Sampled-vs-greedy decode throughput regression guard: tokens and
        # wall seconds accumulated per decode program mode by the step loop.
        self.decode_mode_tokens = {"greedy": 0, "sampled": 0}
        self.decode_mode_wall_s = {"greedy": 0.0, "sampled": 0.0}
        # Mixed (stall-free) batching: device steps by kind plus the
        # cumulative prefill/decode token split of mixed steps — feeds the
        # kgct_mixed_step_ratio gauge and the bench mixed readout.
        self.step_kind_counts = {"prefill": 0, "decode": 0, "mixed": 0,
                                 "spec": 0, "spec_mixed": 0}
        self.mixed_prefill_tokens = 0
        self.mixed_decode_tokens = 0
        # Speculative decoding: cumulative drafted vs accepted draft tokens
        # (bonus tokens excluded from both) — feeds the
        # kgct_spec_acceptance_ratio gauge, the kgct_spec_*_tokens_total
        # counters, and the bench speculative readout.
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        # Draft PHASE telemetry (n-gram lookups or draft-model dispatches,
        # measured at the proposer seam): tokens the proposer actually
        # produced and the wall time spent producing them — splits a spec
        # step's cost into draft vs verify. Zero-safe when spec is off.
        self.spec_draft_tokens = 0
        self.spec_draft_latency = Histogram(
            "kgct_spec_draft_seconds",
            "draft-phase wall time per spec step (proposer seam)")
        # Acceptance-adaptive k: the controller's live rung (None = spec
        # off -> the gauge is absent from /metrics, never NaN).
        self.spec_current_k = None
        # Two-tier KV cache: pages moved device<->host (preempt-by-swap +
        # prefix-spill) and the per-transfer latency split by direction —
        # feeds kgct_kv_swap_{out,in}_pages_total and kgct_kv_swap_seconds.
        self.swap_pages = {"out": 0, "in": 0}
        self.swap_latency = Histogram(
            "kgct_kv_swap_seconds", "host<->device KV page transfer latency",
            labels=("dir",))
        # Fleet-wide prefix cache (serving/fleet_cache.py): remote prefix
        # pulls by outcome — "ok" (imported into the local cache),
        # "recompute" (pull failed/timed out/peer missed: local prefill
        # serves it, byte-identical), "skipped" (the roofline gate priced
        # the pull above recompute, or the prefix was already local) — and
        # remote spills by outcome — "ok" (a peer parked the evicted
        # page), "dropped" (bounded queue displaced it / peer had no
        # room), "error" (push failed). Pre-seeded so a fresh scrape
        # renders zeros for every outcome, nan-free, fleet cache off
        # included.
        self.fleet_pulls = {"ok": 0, "recompute": 0, "skipped": 0}
        self.fleet_spills = {"ok": 0, "dropped": 0, "error": 0}
        self.fleet_bytes = {"pull": 0, "spill": 0}
        self.fleet_pull_latency = Histogram(
            "kgct_fleet_prefix_pull_seconds",
            "remote prefix pull wall latency (fetch + streamed import)")
        # KV wire integrity (serving/handoff.py): detections by wire path
        # x outcome — "corrupt" (a frame failed its own checksums) and
        # "skew" (a peer spoke the pre-integrity dialect to a receiver
        # that requires checksums). Every cell pre-seeded: a fresh scrape
        # renders zeros for the full matrix, integrity off included.
        self.wire_corruptions = {
            (path, outcome): 0
            for path in ("handoff", "prefix", "spill", "migrate", "resume")
            for outcome in ("corrupt", "skew")}
        # Peer quarantine entries by peer URL. Bounded cardinality: the
        # label set is the configured allowlists (peer pool + prefill
        # pool), seeded at server construction so idle peers render 0.
        self.peer_quarantines: dict = {}

    # -- multi-tenant QoS ----------------------------------------------------

    def configure_qos_tiers(self, tiers, default_tier: str,
                            fallback_budget_ms=None) -> None:
        """Install the per-tier SLO trackers: one per CONFIGURED tier
        (bounded cardinality), graded against the tier's own TTFT budget
        when it has one, else ``fallback_budget_ms`` — the operator's
        admission default, so a tier child and the global tracker grade
        the same request against the same bar (None keeps the north-star
        default, matching the global tracker's own fallback). Called once
        at engine construction when QoS is on."""
        self.slo_by_tier = {
            t.name: SLOTracker(ttft_budget_ms=(
                t.ttft_budget_ms if t.ttft_budget_ms is not None
                else fallback_budget_ms))
            for t in tiers}
        self.finished_by_tier = {t.name: 0 for t in tiers}
        self._qos_default_tier = default_tier

    def _tier_slo(self, seq) -> "Optional[SLOTracker]":
        if not self.slo_by_tier:
            return None
        name = getattr(getattr(seq, "params", None), "qos_tier", None)
        if name not in self.slo_by_tier:
            name = self._qos_default_tier
        return self.slo_by_tier.get(name)

    # -- request lifecycle hooks (engine + scheduler) ------------------------

    def on_arrival(self, seq) -> None:
        self.tracer.emit("arrival", seq.request_id,
                         prompt_tokens=seq.num_prompt_tokens)

    def on_queued(self, seq, depth: int = 0) -> None:
        self.tracer.emit("queued", seq.request_id, queue_depth=depth)

    def on_scheduled(self, seq, n_batch: int) -> None:
        resumed = getattr(seq, "preempt_count", 0) > 0
        if seq.scheduled_time is None:
            seq.scheduled_time = time.monotonic()
            self.queue_wait.observe(seq.scheduled_time - seq.arrival_time)
        self.tracer.emit("resume" if resumed else "scheduled",
                         seq.request_id, batch=n_batch)

    def on_prefill_chunk(self, seq, start: int, end: int, total: int) -> None:
        self.tracer.emit("prefill_chunk", seq.request_id,
                         start=start, end=end, total=total)

    def on_preempt(self, seq, kind: str = "recompute") -> None:
        seq.preempt_count += 1
        self.tracer.emit("preempt", seq.request_id, preempt_kind=kind,
                         preempt_count=seq.preempt_count)

    def on_swap(self, direction: str, pages: int, duration_s: float,
                request_id: str = "") -> None:
        """One two-tier KV transfer: ``direction`` "out" (device->host) or
        "in" (host->device), ``pages`` moved, wall latency including the
        host-side copy."""
        if direction in self.swap_pages:
            self.swap_pages[direction] += pages
        self.swap_latency.observe(duration_s, (direction,))
        self.tracer.emit("swap", request_id, dir=direction, pages=pages)

    def on_fleet_pull(self, outcome: str, n_bytes: int = 0,
                      duration_s=None) -> None:
        """One fleet-cache pull decision/attempt (bounded outcome set —
        unknown spellings fold into "recompute" so label cardinality can
        never grow)."""
        if outcome not in self.fleet_pulls:
            outcome = "recompute"
        self.fleet_pulls[outcome] += 1
        self.fleet_bytes["pull"] += n_bytes
        if duration_s is not None:
            self.fleet_pull_latency.observe(duration_s)

    def on_fleet_spill(self, outcome: str, n_bytes: int = 0) -> None:
        """One remote-spill attempt (sender side)."""
        if outcome not in self.fleet_spills:
            outcome = "error"
        self.fleet_spills[outcome] += 1
        self.fleet_bytes["spill"] += n_bytes

    def on_wire_corruption(self, path: str, outcome: str = "corrupt"
                           ) -> None:
        """One integrity detection on a KV wire path (bounded label
        matrix — unknown spellings fold into handoff/corrupt so
        cardinality can never grow)."""
        if (path, outcome) not in self.wire_corruptions:
            path, outcome = "handoff", "corrupt"
        self.wire_corruptions[(path, outcome)] += 1

    def seed_peers(self, peers) -> None:
        """Pre-seed the quarantine counter's label set from the
        configured allowlists — zeros for every known peer on a fresh
        scrape, and the only way labels enter (bounded cardinality)."""
        for peer in peers:
            self.peer_quarantines.setdefault(peer, 0)

    def on_peer_quarantine(self, peer: str) -> None:
        """One quarantine ENTRY for ``peer`` (window extensions do not
        re-count)."""
        self.peer_quarantines[peer] = self.peer_quarantines.get(peer, 0) + 1

    def on_spec_draft(self, n_tokens: int, duration_s: float) -> None:
        """One draft phase (the proposer-seam call of a spec round):
        tokens proposed + wall time. Called by the verifier/spec-mixed
        builders on the worker thread."""
        self.spec_draft_tokens += n_tokens
        self.spec_draft_latency.observe(duration_s)

    def on_first_token(self, seq, fetch_s: float = 0.0) -> None:
        ttft = seq.first_token_time - seq.arrival_time
        self.ttft.observe(ttft, (_outcome(seq, None),))
        self.slo.on_first_token(ttft)
        tier_slo = self._tier_slo(seq)
        if tier_slo is not None:
            tier_slo.on_first_token(ttft)
        queue = ((seq.scheduled_time - seq.arrival_time)
                 if seq.scheduled_time is not None else 0.0)
        prefill = max(ttft - queue - fetch_s, 0.0)
        if seq.scheduled_time is not None:
            self.prefill_latency.observe(prefill)
        self.ttft_queue_s.append(queue)
        self.ttft_prefill_s.append(prefill)
        self.ttft_fetch_s.append(fetch_s)
        self.tracer.emit("first_token", seq.request_id,
                         ttft_ms=round(ttft * 1e3, 2))

    def on_handoff_first_token(self, seq, ttft_s: float) -> None:
        """Disaggregated import: the first token(s) arrived WITH the KV
        handoff, so step()'s first-token transition never fires here.
        ``ttft_s`` is the decode-replica-observed span (remote prefill +
        transfer + import) — the client-facing quantity; it feeds the TTFT
        histogram and the SLO window, and is stashed on the sequence so
        on_finish's goodput gate judges the real latency, not the ~0 of
        first_token_time - arrival_time."""
        seq.handoff_ttft_s = ttft_s
        self.ttft.observe(ttft_s, (_outcome(seq, None),))
        self.slo.on_first_token(ttft_s)
        tier_slo = self._tier_slo(seq)
        if tier_slo is not None:
            tier_slo.on_first_token(ttft_s)
        self.tracer.emit("first_token", seq.request_id,
                         ttft_ms=round(ttft_s * 1e3, 2), handoff=True)

    def on_finish(self, seq, reason) -> None:
        """Terminal accounting — idempotent (several engine paths can reach a
        finished sequence: defer/drain, abort-in-flight, capacity kill)."""
        if seq.finish_time is not None:
            return
        seq.finish_time = time.monotonic()
        outcome = _outcome(seq, reason)
        self.e2e_latency.observe(seq.finish_time - seq.arrival_time,
                                 (outcome,))
        n = seq.num_output_tokens
        # Goodput counts DELIVERED work only: an aborted request's tokens
        # were generated but nobody received them (client disconnect /
        # group-abort), and counting them would overstate the autoscaler's
        # throughput signal under client churn.
        if seq.first_token_time is not None and outcome != "aborted":
            ttft = (seq.handoff_ttft_s
                    if getattr(seq, "handoff_ttft_s", None) is not None
                    else seq.first_token_time - seq.arrival_time)
            self.slo.on_finish(ttft, n)
            tier_slo = self._tier_slo(seq)
            if tier_slo is not None:
                tier_slo.on_finish(ttft, n)
        if self.finished_by_tier and outcome != "aborted":
            name = getattr(getattr(seq, "params", None), "qos_tier", None)
            if name not in self.finished_by_tier:
                name = self._qos_default_tier
            if name in self.finished_by_tier:
                self.finished_by_tier[name] += 1
        if seq.first_token_time is not None and n >= 2:
            self.tpot.observe(
                (seq.finish_time - seq.first_token_time) / (n - 1))
        self.tracer.emit("abort" if outcome == "aborted" else "finish",
                         seq.request_id, outcome=outcome, output_tokens=n)

    # -- step accounting (engine.step) ---------------------------------------

    def on_step(self, step: int, kind: str, batch: int, duration_s: float,
                new_tokens: int, mode: str = None, prefill_tokens: int = 0,
                decode_tokens: int = 0, drafted_tokens: int = 0,
                accepted_tokens: int = 0, draft_s: float = 0.0) -> None:
        # Flight-recorder state snapshot, at most once per interval: one
        # monotonic read per step when nothing is due.
        self.flight.maybe_snapshot()
        self.step_duration.observe(duration_s)
        self.batch_size.observe(batch)
        self.phases.end_step(step=step, kind=kind, batch=batch,
                             duration_s=duration_s)
        if kind in self.step_kind_counts:
            self.step_kind_counts[kind] += 1
        if kind == "decode":
            self.tracer.emit("decode", "", batch=batch, tokens=new_tokens,
                             mode=mode or "greedy")
            if mode in self.decode_mode_tokens:
                self.decode_mode_tokens[mode] += new_tokens
                self.decode_mode_wall_s[mode] += duration_s
        elif kind == "mixed":
            # The stall-free batching signal: how this step's token budget
            # split between the prefill chunk and the decode rows.
            self.mixed_prefill_tokens += prefill_tokens
            self.mixed_decode_tokens += decode_tokens
            self.tracer.emit("mixed", "", batch=batch,
                             prefill_tokens=prefill_tokens,
                             decode_tokens=decode_tokens)
        elif kind == "spec":
            # The speculative-decoding signal: of the drafts this step
            # verified, how many committed (emitted tokens = accepted +
            # one bonus per row; new_tokens carries the realized total).
            # draft/verify phase attribution: the draft half is the
            # proposer-seam wall time, the verify half is the rest of the
            # step (dispatch + fetch of the one verify program).
            self.spec_drafted_tokens += drafted_tokens
            self.spec_accepted_tokens += accepted_tokens
            self.tracer.emit("spec", "", batch=batch, tokens=new_tokens,
                             drafted=drafted_tokens, accepted=accepted_tokens,
                             mode=mode or "greedy",
                             draft_ms=round(draft_s * 1e3, 3),
                             verify_ms=round(
                                 max(duration_s - draft_s, 0.0) * 1e3, 3))
        elif kind == "spec_mixed":
            # The composition step counts BOTH ways: its chunk/verify token
            # split feeds the mixed-batching counters (a spec_mixed step IS
            # a stall-free step) and its draft outcome feeds the spec
            # acceptance counters.
            self.mixed_prefill_tokens += prefill_tokens
            self.mixed_decode_tokens += decode_tokens
            self.spec_drafted_tokens += drafted_tokens
            self.spec_accepted_tokens += accepted_tokens
            self.tracer.emit("spec_mixed", "", batch=batch,
                             tokens=new_tokens,
                             prefill_tokens=prefill_tokens,
                             drafted=drafted_tokens,
                             accepted=accepted_tokens,
                             mode=mode or "greedy",
                             draft_ms=round(draft_s * 1e3, 3),
                             verify_ms=round(
                                 max(duration_s - draft_s, 0.0) * 1e3, 3))

    def mixed_step_ratio(self):
        """Fraction of device steps that carried a prefill chunk alongside
        decode work — plain mixed AND spec×mixed steps both count (a
        spec_mixed step is a stall-free step whose decode half happens to
        be verify slices), or None before any step ran. Near-zero under
        mixing-off or idle-prefill regimes; rises with sustained load when
        stall-free batching is doing its job."""
        total = sum(self.step_kind_counts.values())
        if total <= 0:
            return None
        return (self.step_kind_counts["mixed"]
                + self.step_kind_counts["spec_mixed"]) / total

    def spec_acceptance_ratio(self):
        """accepted/drafted draft tokens over all spec steps, or None
        before any spec step ran. The capacity signal for n-gram drafting:
        near-0 means the workload has no lookup structure (spec steps are
        pure overhead — disable or switch proposers); the bench's
        repetitive-suffix phase expects it high."""
        if self.spec_drafted_tokens <= 0:
            return None
        return self.spec_accepted_tokens / self.spec_drafted_tokens

    def sampled_decode_ratio(self):
        """sampled/greedy decode tok/s ratio, or None until both modes have
        run (round-4 target: >= 0.9)."""
        tg, ts = self.decode_mode_tokens["greedy"], self.decode_mode_tokens["sampled"]
        wg, ws = self.decode_mode_wall_s["greedy"], self.decode_mode_wall_s["sampled"]
        if tg <= 0 or ts <= 0 or wg <= 0 or ws <= 0:
            return None
        return (ts / ws) / (tg / wg)

    # -- rendering / export --------------------------------------------------

    def ttft_decomposition(self) -> dict:
        """Median queue / prefill / first-fetch split of recent TTFTs (ms) —
        the decomposition bench.py reports and QoS PRs will regress against."""
        def med_ms(xs):
            xs = sorted(xs)
            return round(xs[len(xs) // 2] * 1e3, 2) if xs else 0.0
        return {"queue_ms": med_ms(self.ttft_queue_s),
                "prefill_ms": med_ms(self.ttft_prefill_s),
                "first_fetch_ms": med_ms(self.ttft_fetch_s),
                "samples": len(self.ttft_queue_s)}

    def render_prometheus(self) -> list[str]:
        lines: list[str] = []
        for hist in (self.ttft, self.tpot, self.queue_wait,
                     self.prefill_latency, self.step_duration,
                     self.batch_size, self.e2e_latency):
            lines.extend(hist.render())
        lines.append("# TYPE kgct_step_phase_seconds_total counter")
        for p in PHASES:
            lines.append(
                "kgct_step_phase_seconds_total{phase=\"%s\"} %s"
                % (p, fmt(round(self.phases.totals.get(p, 0.0), 6))))
        # Per-phase mean step time, promoted from the tracer's breakdown so
        # dashboards read "where a step's wall time goes" without computing
        # rate ratios; zeros before any step — a fresh scrape is nan-free.
        lines.append("# TYPE kgct_step_phase_mean_seconds gauge")
        for p in PHASES:
            n = self.phases.counts.get(p, 0)
            mean = self.phases.totals.get(p, 0.0) / n if n else 0.0
            lines.append(
                "kgct_step_phase_mean_seconds{phase=\"%s\"} %s"
                % (p, fmt(round(mean, 9))))
        # Rolling SLO layer (autoscaler signals, ROADMAP 4(b)): attainment
        # of the admission-control TTFT budget over recent requests, the
        # budget itself, and budget-meeting goodput. 1.0 / 0.0 when fresh.
        # Multi-tenant QoS: the attainment/goodput families gain a
        # bounded-cardinality ``tier`` label (values = configured tier
        # names only), rendered inside each family's TYPE block. Absent
        # entirely when QoS is off; zeros/1.0-safe on a fresh scrape (an
        # empty window has missed nothing).
        tier_names = sorted(self.slo_by_tier)
        lines += [
            "# TYPE kgct_slo_ttft_budget_ms gauge",
            f"kgct_slo_ttft_budget_ms {fmt(self.slo.budget_ms)}",
            "# TYPE kgct_slo_ttft_attainment_ratio gauge",
            "kgct_slo_ttft_attainment_ratio "
            f"{fmt(round(self.slo.attainment(), 6))}",
        ]
        lines += [
            f'kgct_slo_ttft_attainment_ratio{{tier="{n}"}} '
            f"{fmt(round(self.slo_by_tier[n].attainment(), 6))}"
            for n in tier_names]
        lines += [
            "# TYPE kgct_slo_goodput_tokens_per_sec gauge",
            "kgct_slo_goodput_tokens_per_sec "
            f"{fmt(round(self.slo.goodput_tokens_per_sec(), 3))}",
        ]
        lines += [
            f'kgct_slo_goodput_tokens_per_sec{{tier="{n}"}} '
            f"{fmt(round(self.slo_by_tier[n].goodput_tokens_per_sec(), 3))}"
            for n in tier_names]
        if self.finished_by_tier:
            lines.append("# TYPE kgct_qos_requests_finished_total counter")
            for name in sorted(self.finished_by_tier):
                lines.append(
                    f'kgct_qos_requests_finished_total{{tier="{name}"}} '
                    f"{self.finished_by_tier[name]}")
        lines.extend(render_gauge("kgct_sampled_decode_ratio",
                                  self.sampled_decode_ratio()))
        lines.extend(render_gauge("kgct_mixed_step_ratio",
                                  self.mixed_step_ratio()))
        lines.append("# TYPE kgct_mixed_prefill_tokens_total counter")
        lines.append("kgct_mixed_prefill_tokens_total %d"
                     % self.mixed_prefill_tokens)
        lines.append("# TYPE kgct_mixed_decode_tokens_total counter")
        lines.append("kgct_mixed_decode_tokens_total %d"
                     % self.mixed_decode_tokens)
        lines.extend(render_gauge("kgct_spec_acceptance_ratio",
                                  self.spec_acceptance_ratio()))
        lines.append("# TYPE kgct_spec_drafted_tokens_total counter")
        lines.append("kgct_spec_drafted_tokens_total %d"
                     % self.spec_drafted_tokens)
        lines.append("# TYPE kgct_spec_accepted_tokens_total counter")
        lines.append("kgct_spec_accepted_tokens_total %d"
                     % self.spec_accepted_tokens)
        # Acceptance-adaptive k: the live rung. Absent when spec is off
        # (None), present from engine construction when on — a fresh
        # scrape is nan-free either way.
        lines.extend(render_gauge("kgct_spec_current_k",
                                  self.spec_current_k))
        lines.append("# TYPE kgct_spec_draft_tokens_total counter")
        lines.append("kgct_spec_draft_tokens_total %d"
                     % self.spec_draft_tokens)
        lines.extend(self.spec_draft_latency.render())
        lines.append("# TYPE kgct_kv_swap_out_pages_total counter")
        lines.append("kgct_kv_swap_out_pages_total %d"
                     % self.swap_pages["out"])
        lines.append("# TYPE kgct_kv_swap_in_pages_total counter")
        lines.append("kgct_kv_swap_in_pages_total %d" % self.swap_pages["in"])
        lines.extend(self.swap_latency.render())
        # Fleet-wide prefix cache: every outcome pre-seeded — zeros when
        # the fleet cache is off or idle, never an absent series.
        lines.append("# TYPE kgct_fleet_prefix_pulls_total counter")
        for oc in sorted(self.fleet_pulls):
            lines.append(f'kgct_fleet_prefix_pulls_total{{outcome="{oc}"}} '
                         f"{self.fleet_pulls[oc]}")
        lines.append("# TYPE kgct_fleet_prefix_spills_total counter")
        for oc in sorted(self.fleet_spills):
            lines.append(f'kgct_fleet_prefix_spills_total{{outcome="{oc}"}} '
                         f"{self.fleet_spills[oc]}")
        lines.append("# TYPE kgct_fleet_prefix_bytes_total counter")
        for d in sorted(self.fleet_bytes):
            lines.append(f'kgct_fleet_prefix_bytes_total{{dir="{d}"}} '
                         f"{self.fleet_bytes[d]}")
        lines.extend(self.fleet_pull_latency.render())
        # KV wire integrity: the full path x outcome matrix pre-seeded.
        lines.append("# TYPE kgct_kv_wire_corruptions_total counter")
        for (path, oc) in sorted(self.wire_corruptions):
            lines.append(
                f'kgct_kv_wire_corruptions_total{{path="{path}",'
                f'outcome="{oc}"}} {self.wire_corruptions[(path, oc)]}')
        # Peer quarantines: labels only from the seeded allowlists.
        lines.append("# TYPE kgct_peer_quarantines_total counter")
        for peer in sorted(self.peer_quarantines):
            lines.append(f'kgct_peer_quarantines_total{{peer="{peer}"}} '
                         f"{self.peer_quarantines[peer]}")
        return lines

    def export_perfetto(self) -> dict:
        return self.tracer.export_perfetto(
            step_records=(self.phases.step_records()
                          + self.phases.detached_records()))

    def clear_trace(self) -> None:
        """Empty every trace ring (lifecycle events, step-phase records,
        detached slices) for a scoped capture; histogram/total state — the
        /metrics contract — is untouched."""
        self.tracer.clear()
        self.phases.clear_records()
