"""Minimal Prometheus primitives for the serving stack.

The /metrics surface graduated from two-point summaries (p50/p95 computed
host-side, useless for cross-replica aggregation) to real histograms:
``_bucket``/``_sum``/``_count`` exposition lets Prometheus compute any
quantile across replicas and time windows, which the north-star metric
("p50 TTFT under continuous batching") needs once more than one replica
serves. No client library is baked into the image, so this is the text
exposition format written by hand — same approach as serving/metrics.py.

Rendering is nan-free by construction: an empty histogram renders all-zero
buckets (a freshly started server must scrape cleanly), and cumulative
bucket counts are monotone because they are accumulated that way.
"""

from __future__ import annotations

from typing import Optional

# Latency buckets (seconds): µs-scale device steps up to multi-second TTFT
# under load — covers the 3.4 s p50 sustained-load regime VERDICT r5 flagged.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Batch-size-per-step buckets: powers of two matching the scheduler's padded
# decode buckets, so the histogram reads as "which compiled shape ran".
BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)


def fmt(v: float) -> str:
    """Exposition-safe number: integral floats render without the trailing
    .0 churn, everything else with enough precision to be useful."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Histogram:
    """A labeled cumulative histogram in Prometheus text exposition format.

    ``labels``: optional tuple of label NAMES; each observe() then supplies
    the matching label VALUES. One (counts, sum, count) cell per labelset.
    """

    def __init__(self, name: str, help_text: str = "",
                 buckets: tuple = LATENCY_BUCKETS_S,
                 labels: tuple = ()):
        self.name = name
        self.help_text = help_text
        self.buckets = tuple(sorted(buckets))
        self.label_names = tuple(labels)
        # labelset (tuple of values) -> [per-bucket counts, sum, count]
        self._cells: dict[tuple, list] = {}
        if not self.label_names:
            self._cells[()] = [[0] * len(self.buckets), 0.0, 0]

    def observe(self, value: float, label_values: tuple = ()) -> None:
        if value != value:          # nan never enters the exposition
            return
        cell = self._cells.get(label_values)
        if cell is None:
            cell = self._cells[label_values] = [[0] * len(self.buckets),
                                                0.0, 0]
        # Count and sum BEFORE the bucket: the engine worker thread observes
        # while the HTTP thread renders, and render() snapshots buckets
        # before reading the count — this ordering guarantees every bucket
        # increment a render sees is already in its count, so the scrape's
        # cumulative buckets never exceed +Inf/_count (the monotonicity
        # strict parsers and the exposition validator enforce).
        cell[1] += value
        cell[2] += 1
        counts, _, _ = cell
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                counts[i] += 1
                break

    @property
    def count(self) -> int:
        return sum(cell[2] for cell in self._cells.values())

    @property
    def sum(self) -> float:
        return sum(cell[1] for cell in self._cells.values())

    def merged_counts(self) -> list:
        """Per-bucket counts summed over all labelsets, with one extra
        trailing cell for observations ABOVE the last finite bound (observe
        drops those from the bucket array; the quantile must still rank
        them). A snapshot callers can difference against a later one for
        WINDOWED quantiles (counts only grow, so deltas stay valid)."""
        merged = [0] * (len(self.buckets) + 1)
        for cell in self._cells.values():
            finite = 0
            for i, c in enumerate(cell[0]):
                merged[i] += c
                finite += c
            merged[-1] += cell[2] - finite
        return merged

    def quantile(self, q: float) -> float:
        """Histogram-quantile over all labelsets, Prometheus-style (see
        quantile_from_counts). Served live to the admission controller, so
        it reads under concurrent observe(): bucket counts are snapshotted
        by merged_counts first."""
        return quantile_from_counts(self.buckets, self.merged_counts(), q)

    def _labelstr(self, values: tuple, extra: str = "") -> str:
        pairs = [f'{k}="{v}"' for k, v in zip(self.label_names, values)]
        if extra:
            pairs.append(extra)
        return "{" + ",".join(pairs) + "}" if pairs else ""

    def render(self) -> list[str]:
        lines = [f"# TYPE {self.name} histogram"]
        if self.help_text:
            lines.insert(0, f"# HELP {self.name} {self.help_text}")
        for values, cell in sorted(self._cells.items()):
            # Snapshot buckets BEFORE reading count (see observe's ordering
            # comment): cum <= n even mid-observe on another thread.
            counts = list(cell[0])
            total, n = cell[1], cell[2]
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                le = 'le="' + fmt(bound) + '"'
                lines.append(
                    f"{self.name}_bucket{self._labelstr(values, le)} {cum}")
            inf_le = 'le="+Inf"'
            lines.append(
                f"{self.name}_bucket{self._labelstr(values, inf_le)} {n}")
            lines.append(f"{self.name}_sum{self._labelstr(values)} "
                         f"{fmt(round(total, 6))}")
            lines.append(f"{self.name}_count{self._labelstr(values)} {n}")
        return lines


def quantile_from_counts(buckets: tuple, counts: list, q: float) -> float:
    """Prometheus-style histogram quantile over per-bucket counts (counts
    may carry one extra trailing overflow cell, merged_counts-style): find
    the bucket holding the q-th observation and interpolate linearly inside
    it (lower bound 0 for the first bucket; overflow observations clamp to
    the last finite bound). 0.0 on an empty set — callers treat "no data"
    as "no wait", the right admission-control default for a fresh server."""
    n = sum(counts)
    if n <= 0:
        return 0.0
    rank = q * n
    cum = 0
    lo = 0.0
    for bound, c in zip(buckets, counts):
        if cum + c >= rank and c > 0:
            frac = (rank - cum) / c
            return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        cum += c
        lo = bound
    return buckets[-1]


def render_gauge(name: str, value: Optional[float],
                 labels: str = "") -> list[str]:
    """One gauge sample; None/nan values render NOTHING (a fresh server must
    scrape cleanly, and Prometheus treats an absent series correctly where a
    0 or nan would lie)."""
    if value is None or value != value:
        return []
    return [f"# TYPE {name} gauge", f"{name}{labels} {fmt(round(value, 6))}"]
