"""Structured logging for the framework.

The reference repo's only observability was colored bash ``log/warn/error``
helpers (reference ``k8s_setup.sh:49-51``, ``gpu-crio-setup.sh:9-11``). Here we
provide structured, leveled logging shared by the engine, server, and cluster
tools, controllable via ``KGCT_LOG_LEVEL`` (mirroring the reference's debug
knobs like ``VLLM_LOGGING_LEVEL`` / ``NVIDIA_LOG_LEVEL``,
reference ``old_README.md:998-1002,1130``).

``KGCT_LOG_FORMAT=json`` switches to one-JSON-object-per-line output with a
``request_id`` field whenever a log call carries one
(``logger.info(..., extra={"request_id": rid})``) — the same ids the
request-lifecycle tracer records, so a log pipeline (Loki/ELK) joins logs
with ``/debug/trace`` spans on the id. Logs always go to stderr: stdout is
reserved for program output (bench.py's result line depends on this).
"""

import json
import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"
_configured = False


class _JsonFormatter(logging.Formatter):
    """One JSON object per line: ts (unix seconds), level, logger, msg, plus
    request_id when the call site attached one via ``extra`` — machine-
    parseable and joinable with the trace/metrics surfaces on request id."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        rid = getattr(record, "request_id", None)
        if rid is not None:
            entry["request_id"] = rid
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


def _make_formatter() -> logging.Formatter:
    if os.environ.get("KGCT_LOG_FORMAT", "").lower() == "json":
        return _JsonFormatter()
    return logging.Formatter(_FORMAT, datefmt="%H:%M:%S")


def _configure_root() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("KGCT_LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_make_formatter())
    root = logging.getLogger("kgct")
    root.setLevel(level)
    root.addHandler(handler)
    root.propagate = False
    _configured = True


def get_logger(name: str) -> logging.Logger:
    """Return a logger namespaced under the framework root ``kgct``."""
    _configure_root()
    return logging.getLogger(f"kgct.{name}")
