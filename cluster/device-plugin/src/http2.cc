#include "http2.h"

#include <errno.h>
#include <string.h>
#include <unistd.h>

#include <algorithm>

namespace kgct {
namespace {

constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = 24;

enum FrameType : uint8_t {
  kData = 0x0,
  kHeaders = 0x1,
  kPriority = 0x2,
  kRstStream = 0x3,
  kSettings = 0x4,
  kPushPromise = 0x5,
  kPing = 0x6,
  kGoAway = 0x7,
  kWindowUpdate = 0x8,
  kContinuation = 0x9,
};

enum Flags : uint8_t {
  kEndStream = 0x1,
  kAck = 0x1,
  kEndHeaders = 0x4,
  kPadded = 0x8,
  kPriorityFlag = 0x20,
};

uint32_t U32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

}  // namespace

Http2Conn::Http2Conn(int fd, Role role, Events events)
    : fd_(fd), role_(role), events_(std::move(events)) {}

void Http2Conn::Handshake() {
  if (role_ == Role::kClient) WriteAll(kPreface, kPrefaceLen);
  WriteFrame(kSettings, 0, 0, "");  // defaults are fine for both roles
  // Open up the receive side: a large connection window so peers never stall
  // on us (we consume immediately).
  std::string wu(4, '\0');
  uint32_t inc = (1u << 30);
  wu[0] = char(inc >> 24), wu[1] = char(inc >> 16);
  wu[2] = char(inc >> 8), wu[3] = char(inc);
  WriteFrame(kWindowUpdate, 0, 0, wu);
}

Http2Conn::Stream& Http2Conn::GetStream(uint32_t id) {
  auto [it, inserted] = streams_.try_emplace(id);
  if (inserted) it->second.send_window = peer_initial_window_;
  return it->second;
}

uint32_t Http2Conn::NextStreamId() {
  uint32_t id = next_stream_id_;
  next_stream_id_ += 2;
  return id;
}

void Http2Conn::WriteAll(const void* p, size_t n) {
  const char* c = static_cast<const char*>(p);
  while (n > 0) {
    ssize_t w = ::write(fd_, c, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      throw Http2Error(std::string("write: ") + strerror(errno));
    }
    c += w;
    n -= static_cast<size_t>(w);
  }
}

void Http2Conn::WriteFrame(uint8_t type, uint8_t flags, uint32_t stream,
                           const std::string& payload) {
  uint8_t hdr[9];
  size_t n = payload.size();
  hdr[0] = uint8_t(n >> 16), hdr[1] = uint8_t(n >> 8), hdr[2] = uint8_t(n);
  hdr[3] = type;
  hdr[4] = flags;
  hdr[5] = uint8_t(stream >> 24) & 0x7f;
  hdr[6] = uint8_t(stream >> 16);
  hdr[7] = uint8_t(stream >> 8);
  hdr[8] = uint8_t(stream);
  std::string buf(reinterpret_cast<char*>(hdr), 9);
  buf += payload;
  WriteAll(buf.data(), buf.size());
}

void Http2Conn::SendHeaders(uint32_t stream, const std::vector<Header>& headers,
                            bool end_stream) {
  std::string block = HpackEncode(headers);
  // Our header blocks are far below any MAX_FRAME_SIZE; one frame suffices.
  WriteFrame(kHeaders, kEndHeaders | (end_stream ? kEndStream : 0), stream,
             block);
  Stream& st = GetStream(stream);
  if (end_stream) st.closed_local = true;
}

void Http2Conn::SendData(uint32_t stream, const std::string& payload,
                         bool end_stream) {
  Stream& st = GetStream(stream);
  st.pending += payload;
  st.pending_end = st.pending_end || end_stream;
  TrySend(stream, st);
}

void Http2Conn::TrySend(uint32_t stream, Stream& st) {
  while (!st.pending.empty() || (st.pending_end && !st.closed_local)) {
    size_t budget = static_cast<size_t>(
        std::min<int64_t>(std::max<int64_t>(conn_send_window_, 0),
                          std::max<int64_t>(st.send_window, 0)));
    size_t n = std::min({st.pending.size(),
                         static_cast<size_t>(peer_max_frame_),
                         budget > 0 ? budget : 0});
    if (n == 0 && !st.pending.empty()) return;  // wait for WINDOW_UPDATE
    bool last = (n == st.pending.size()) && st.pending_end;
    WriteFrame(kData, last ? kEndStream : 0, stream, st.pending.substr(0, n));
    st.pending.erase(0, n);
    conn_send_window_ -= static_cast<int64_t>(n);
    st.send_window -= static_cast<int64_t>(n);
    if (last) {
      st.closed_local = true;
      return;
    }
    if (st.pending.empty()) return;
  }
}

void Http2Conn::SendRstStream(uint32_t stream, uint32_t error_code) {
  std::string p(4, '\0');
  p[0] = char(error_code >> 24), p[1] = char(error_code >> 16);
  p[2] = char(error_code >> 8), p[3] = char(error_code);
  WriteFrame(kRstStream, 0, stream, p);
  streams_.erase(stream);
}

void Http2Conn::SendGoAway(uint32_t error_code) {
  std::string p(8, '\0');
  // last stream id 2^31-1 (we processed everything we saw), then the code.
  p[0] = 0x7f, p[1] = char(0xff), p[2] = char(0xff), p[3] = char(0xff);
  p[4] = char(error_code >> 24), p[5] = char(error_code >> 16);
  p[6] = char(error_code >> 8), p[7] = char(error_code);
  WriteFrame(kGoAway, 0, 0, p);
}

bool Http2Conn::OnReadable() {
  char buf[65536];
  ssize_t r = ::read(fd_, buf, sizeof(buf));
  if (r < 0) {
    if (errno == EINTR || errno == EAGAIN) return true;
    throw Http2Error(std::string("read: ") + strerror(errno));
  }
  if (r == 0) return false;  // peer closed
  inbuf_.append(buf, static_cast<size_t>(r));

  if (role_ == Role::kServer && !preface_seen_) {
    if (inbuf_.size() < kPrefaceLen) return true;
    if (inbuf_.compare(0, kPrefaceLen, kPreface) != 0)
      throw Http2Error("bad client preface");
    inbuf_.erase(0, kPrefaceLen);
    preface_seen_ = true;
  }

  while (inbuf_.size() >= 9) {
    const uint8_t* h = reinterpret_cast<const uint8_t*>(inbuf_.data());
    size_t len = (size_t(h[0]) << 16) | (size_t(h[1]) << 8) | h[2];
    if (len > (1u << 24)) throw Http2Error("oversized frame");
    if (inbuf_.size() < 9 + len) break;
    uint8_t type = h[3], flags = h[4];
    uint32_t stream = U32(h + 5) & 0x7fffffff;
    HandleFrame(type, flags, stream, h + 9, len);
    inbuf_.erase(0, 9 + len);
  }
  return true;
}

void Http2Conn::HandleFrame(uint8_t type, uint8_t flags, uint32_t stream,
                            const uint8_t* p, size_t n) {
  if (in_continuation_ && type != kContinuation)
    throw Http2Error("expected CONTINUATION");
  switch (type) {
    case kSettings:
      HandleSettings(flags, p, n);
      break;
    case kPing:
      if (!(flags & kAck)) {
        if (n != 8) throw Http2Error("bad PING length");
        WriteFrame(kPing, kAck, 0,
                   std::string(reinterpret_cast<const char*>(p), n));
      }
      break;
    case kWindowUpdate: {
      if (n != 4) throw Http2Error("bad WINDOW_UPDATE length");
      uint32_t inc = U32(p) & 0x7fffffff;
      if (stream == 0) {
        conn_send_window_ += inc;
        for (auto& [sid, st] : streams_) TrySend(sid, st);
      } else {
        auto it = streams_.find(stream);
        if (it != streams_.end()) {
          it->second.send_window += inc;
          TrySend(stream, it->second);
        }
      }
      break;
    }
    case kHeaders: {
      if (stream == 0) throw Http2Error("HEADERS on stream 0");
      size_t off = 0, pad = 0;
      if (flags & kPadded) {
        if (n < 1) throw Http2Error("bad padding");
        pad = p[0];
        off = 1;
      }
      if (flags & kPriorityFlag) off += 5;
      if (off + pad > n) throw Http2Error("bad padding");
      header_block_.assign(reinterpret_cast<const char*>(p + off),
                           n - off - pad);
      header_end_stream_ = flags & kEndStream;
      continuation_stream_ = stream;
      if (flags & kEndHeaders) {
        auto hdrs = hpack_in_.Decode(
            reinterpret_cast<const uint8_t*>(header_block_.data()),
            header_block_.size());
        GetStream(stream);
        if (events_.on_headers)
          events_.on_headers(stream, std::move(hdrs), header_end_stream_);
      } else {
        in_continuation_ = true;
      }
      break;
    }
    case kContinuation: {
      if (!in_continuation_ || stream != continuation_stream_)
        throw Http2Error("unexpected CONTINUATION");
      header_block_.append(reinterpret_cast<const char*>(p), n);
      if (flags & kEndHeaders) {
        in_continuation_ = false;
        auto hdrs = hpack_in_.Decode(
            reinterpret_cast<const uint8_t*>(header_block_.data()),
            header_block_.size());
        GetStream(stream);
        if (events_.on_headers)
          events_.on_headers(stream, std::move(hdrs), header_end_stream_);
      }
      break;
    }
    case kData: {
      if (stream == 0) throw Http2Error("DATA on stream 0");
      size_t off = 0, pad = 0;
      if (flags & kPadded) {
        if (n < 1) throw Http2Error("bad padding");
        pad = p[0];
        off = 1;
      }
      if (off + pad > n) throw Http2Error("bad padding");
      std::string payload(reinterpret_cast<const char*>(p + off),
                          n - off - pad);
      // Replenish receive windows immediately — we consume everything.
      if (n > 0) {
        std::string wu(4, '\0');
        uint32_t inc = static_cast<uint32_t>(n);
        wu[0] = char(inc >> 24), wu[1] = char(inc >> 16);
        wu[2] = char(inc >> 8), wu[3] = char(inc);
        WriteFrame(kWindowUpdate, 0, 0, wu);
        if (!(flags & kEndStream)) WriteFrame(kWindowUpdate, 0, stream, wu);
      }
      if (events_.on_data)
        events_.on_data(stream, payload, flags & kEndStream);
      break;
    }
    case kRstStream:
      streams_.erase(stream);
      if (events_.on_rst_stream) events_.on_rst_stream(stream);
      break;
    case kGoAway:
      if (events_.on_goaway) events_.on_goaway();
      break;
    case kPriority:
      break;  // scheduling hint only; ignored
    case kPushPromise:
      throw Http2Error("unexpected PUSH_PROMISE");
    default:
      break;  // unknown frame types MUST be ignored (RFC 7540 §4.1)
  }
}

void Http2Conn::HandleSettings(uint8_t flags, const uint8_t* p, size_t n) {
  if (flags & kAck) return;
  if (n % 6 != 0) throw Http2Error("bad SETTINGS length");
  for (size_t i = 0; i < n; i += 6) {
    uint16_t id = (uint16_t(p[i]) << 8) | p[i + 1];
    uint32_t value = U32(p + i + 2);
    switch (id) {
      case 0x4: {  // INITIAL_WINDOW_SIZE: adjust all open stream windows
        int64_t delta =
            int64_t(value) - int64_t(peer_initial_window_);
        peer_initial_window_ = value;
        for (auto& [sid, st] : streams_) {
          st.send_window += delta;
          TrySend(sid, st);
        }
        break;
      }
      case 0x5:  // MAX_FRAME_SIZE
        peer_max_frame_ = value;
        break;
      default:
        break;  // HEADER_TABLE_SIZE (we never index), others: ignored
    }
  }
  WriteFrame(kSettings, kAck, 0, "");
}

}  // namespace kgct
