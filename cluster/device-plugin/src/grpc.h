// gRPC-over-HTTP/2 on unix sockets: a poll-driven server (unary +
// server-streaming) and a blocking unary client — exactly the two roles a
// kubelet device plugin needs (serve v1beta1.DevicePlugin; dial
// v1beta1.Registration on kubelet.sock).
//
// Framing per the gRPC HTTP/2 spec: requests/responses are length-prefixed
// messages (1-byte compressed flag + u32 big-endian length) inside DATA
// frames; status travels in HTTP trailers (grpc-status/grpc-message);
// errors without a body use trailers-only responses. Compression is not
// supported and flagged messages are rejected (kubelet never compresses).
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "http2.h"

namespace kgct {

// Canonical gRPC status codes (subset used here).
enum GrpcStatus : int {
  kOk = 0,
  kUnknown = 2,
  kNotFound = 5,
  kUnimplemented = 12,
  kInternal = 13,
  kUnavailable = 14,
};

struct GrpcError : std::runtime_error {
  GrpcError(int code, const std::string& msg)
      : std::runtime_error(msg), code(code) {}
  int code;
};

class GrpcServer {
 public:
  // Serialized request bytes in, serialized response bytes out. Throw
  // GrpcError to fail the call with a status.
  using UnaryFn = std::function<std::string(const std::string&)>;

  // Handle to a live server-stream; owned jointly by the server (which
  // invalidates it when the stream/connection dies) and the application
  // (which holds it to push messages later).
  struct StreamHandle {
    bool alive = false;
    Http2Conn* conn = nullptr;
    uint32_t stream = 0;
  };
  using StreamPtr = std::shared_ptr<StreamHandle>;
  using StreamStartFn =
      std::function<void(const std::string& request, StreamPtr)>;

  GrpcServer();  // out-of-line: Conn is incomplete here
  ~GrpcServer();

  void AddUnary(const std::string& path, UnaryFn fn);
  void AddServerStream(const std::string& path, StreamStartFn fn);

  // Binds + listens on a unix socket (unlinks any stale file first).
  void Listen(const std::string& unix_path);
  // Accepts/reads once with the given timeout; dispatches handlers inline.
  void PollOnce(int timeout_ms);

  void StreamSend(const StreamPtr& s, const std::string& message);
  void StreamClose(const StreamPtr& s, int status, const std::string& msg);

  int listen_fd() const { return listen_fd_; }

 private:
  struct Conn;
  void Accept();
  void Dispatch(Conn* c, uint32_t stream);
  void CloseConn(Conn* c);

  int listen_fd_ = -1;
  std::string socket_path_;
  std::map<std::string, UnaryFn> unary_;
  std::map<std::string, StreamStartFn> streams_;
  std::vector<std::unique_ptr<Conn>> conns_;
};

// Blocking unary call. Returns the gRPC status code (0 = OK); on success
// *response holds the serialized reply, otherwise *error the message.
int GrpcUnaryCall(const std::string& unix_path, const std::string& method_path,
                  const std::string& request, std::string* response,
                  std::string* error, int timeout_ms = 5000);

}  // namespace kgct
