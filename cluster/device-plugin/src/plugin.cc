// kgct-tpu-device-plugin: a kubelet device plugin advertising TPU chips as
// `google.com/tpu`, implemented against the kubelet device-plugin gRPC API
// v1beta1 with the embedded gRPC/HTTP2/HPACK stack in this directory.
//
// Role in the framework: the TPU-native replacement for the NVIDIA device
// plugin DaemonSet the reference applied and patched (reference
// `README.md:90`, `old_README.md:1206-1318`, `gpu-crio-setup.sh:87-126`).
// Where the GPU chain needed toolkit + CDI + OCI hooks to inject devices,
// TPU VMs only need the /dev/accel* (or /dev/vfio/*) character devices
// mapped into the container plus TPU_VISIBLE_CHIPS — both served from
// Allocate() here, no runtime hooks required.
//
// Flow (v1beta1 contract):
//   1. serve DevicePlugin on <plugin-dir>/kgct-tpu.sock
//   2. dial <plugin-dir>/kubelet.sock, Registration/Register(endpoint,
//      resource)
//   3. kubelet connects back: ListAndWatch streams the device inventory
//      (re-sent whenever health changes); Allocate returns device specs +
//      envs per container
//   4. if kubelet.sock is recreated (kubelet restart), re-register
//
// Tests: tests/test_device_plugin.py runs this binary against a fake kubelet
// built on grpcio + the real protoc-generated v1beta1 messages, proving
// wire-level interop of the whole embedded stack.

#include <dirent.h>
#include <signal.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "grpc.h"
#include "pb.h"

namespace kgct {
namespace {

struct Options {
  std::string plugin_dir = "/var/lib/kubelet/device-plugins";
  std::string endpoint = "kgct-tpu.sock";
  std::string resource = "google.com/tpu";
  std::string dev_root = "/dev";
  std::string dev_prefix = "accel";
  std::string cdi_spec_path;  // --write-cdi-spec=PATH: emit CDI json + exit
  int health_interval_s = 5;
  bool register_with_kubelet = true;
  bool oneshot = false;  // tests: exit after first ListAndWatch push + idle
};

volatile sig_atomic_t g_stop = 0;
void OnSignal(int) { g_stop = 1; }

// -- v1beta1 message encode/decode (field numbers per the public
// k8s.io/kubelet device-plugin api.proto) ----------------------------------

std::string EncodeDevice(const std::string& id, const std::string& health) {
  PbWriter w;
  w.StringField(1, id);       // Device.ID
  w.StringField(2, health);   // Device.health
  return w.str();
}

std::string EncodeListAndWatchResponse(
    const std::map<std::string, std::string>& devices) {
  PbWriter w;
  for (const auto& [id, health] : devices)
    w.MessageField(1, EncodeDevice(id, health));
  return w.str();
}

std::string EncodeRegisterRequest(const Options& opt) {
  PbWriter options;
  // pre_start_required=false, get_preferred_allocation_available=false:
  // both default -> empty options submessage.
  PbWriter w;
  w.StringField(1, "v1beta1");       // version
  w.StringField(2, opt.endpoint);    // endpoint (basename, kubelet joins dir)
  w.StringField(3, opt.resource);    // resource_name
  w.MessageField(4, options.str());  // options
  return w.str();
}

std::string EncodeMount(const std::string& container_path,
                        const std::string& host_path, bool read_only) {
  PbWriter w;
  w.StringField(1, container_path);
  w.StringField(2, host_path);
  w.BoolField(3, read_only);
  return w.str();
}

std::string EncodeDeviceSpec(const std::string& container_path,
                             const std::string& host_path,
                             const std::string& permissions) {
  PbWriter w;
  w.StringField(1, container_path);
  w.StringField(2, host_path);
  w.StringField(3, permissions);
  return w.str();
}

std::string EncodeEnvEntry(const std::string& k, const std::string& v) {
  // map<string,string> entry: key=1, value=2.
  PbWriter w;
  w.StringField(1, k);
  w.StringField(2, v);
  return w.str();
}

// -- device discovery -------------------------------------------------------

std::map<std::string, std::string> ScanDevices(const Options& opt) {
  std::map<std::string, std::string> devices;  // id -> health
  DIR* d = opendir(opt.dev_root.c_str());
  if (d == nullptr) return devices;
  while (dirent* e = readdir(d)) {
    std::string name = e->d_name;
    if (name.rfind(opt.dev_prefix, 0) != 0) continue;
    std::string rest = name.substr(opt.dev_prefix.size());
    if (rest.empty() ||
        !std::all_of(rest.begin(), rest.end(), [](char c) {
          return c >= '0' && c <= '9';
        }))
      continue;
    struct stat st{};
    std::string path = opt.dev_root + "/" + name;
    bool healthy = stat(path.c_str(), &st) == 0;
    devices[name] = healthy ? "Healthy" : "Unhealthy";
  }
  closedir(d);
  return devices;
}

// -- CDI spec (C19 parity) --------------------------------------------------
// The reference's GPU chain generated /etc/cdi/nvidia.yaml via nvidia-ctk
// (reference gpu-crio-setup.sh:87-101) so CDI-mode runtimes could inject
// devices without the prestart hook. TPU equivalent: enumerate the chips as
// a CDI spec; CRI-O/containerd with CDI enabled can then inject them via
// `cdi.k8s.io/google.com/tpu=<n>` annotations — an alternative to the
// device-plugin Allocate path for non-k8s container runs.

int WriteCdiSpec(const Options& opt, const std::string& path) {
  auto devices = ScanDevices(opt);
  FILE* f = fopen(path.c_str(), "w");
  if (f == nullptr) {
    fprintf(stderr, "[kgct-device-plugin] cannot write %s\n", path.c_str());
    return 1;
  }
  fprintf(f, "{\n  \"cdiVersion\": \"0.6.0\",\n  \"kind\": \"%s\",\n"
             "  \"devices\": [\n", opt.resource.c_str());
  bool first = true;
  for (const auto& [id, health] : devices) {
    (void)health;
    std::string idx = id.substr(opt.dev_prefix.size());
    fprintf(f, "%s    {\n      \"name\": \"%s\",\n      \"containerEdits\": "
               "{\n        \"deviceNodes\": [\n          {\"path\": "
               "\"/dev/%s\", \"hostPath\": \"%s/%s\", \"permissions\": "
               "\"rw\"}\n        ]\n      }\n    }",
            first ? "" : ",\n", idx.c_str(), id.c_str(),
            opt.dev_root.c_str(), id.c_str());
    first = false;
  }
  fprintf(f, "\n  ],\n  \"containerEdits\": {}\n}\n");
  // A truncated spec (ENOSPC etc.) must not report success — the runtime
  // would silently stop injecting devices on a parse failure later.
  bool write_err = ferror(f) != 0;
  if (fclose(f) != 0 || write_err) {
    fprintf(stderr, "[kgct-device-plugin] short write to %s\n", path.c_str());
    ::unlink(path.c_str());
    return 1;
  }
  fprintf(stderr, "[kgct-device-plugin] wrote CDI spec (%zu devices) to %s\n",
          devices.size(), path.c_str());
  return 0;
}

// -- plugin service ---------------------------------------------------------

class Plugin {
 public:
  explicit Plugin(Options opt) : opt_(std::move(opt)) {
    devices_ = ScanDevices(opt_);
    server_.AddUnary(
        "/v1beta1.DevicePlugin/GetDevicePluginOptions",
        [](const std::string&) { return std::string(); });  // all defaults
    server_.AddUnary("/v1beta1.DevicePlugin/Allocate",
                     [this](const std::string& req) { return Allocate(req); });
    server_.AddUnary("/v1beta1.DevicePlugin/PreStartContainer",
                     [](const std::string&) { return std::string(); });
    server_.AddUnary(
        "/v1beta1.DevicePlugin/GetPreferredAllocation",
        [](const std::string&) -> std::string {
          throw GrpcError(kUnimplemented, "preferred allocation not offered");
        });
    server_.AddServerStream(
        "/v1beta1.DevicePlugin/ListAndWatch",
        [this](const std::string&, GrpcServer::StreamPtr s) {
          server_.StreamSend(s, EncodeListAndWatchResponse(devices_));
          watchers_.push_back(std::move(s));
          pushed_once_ = true;
        });
  }

  std::string SocketPath() const { return opt_.plugin_dir + "/" + opt_.endpoint; }
  std::string KubeletSock() const { return opt_.plugin_dir + "/kubelet.sock"; }

  bool Register() {
    std::string resp, err;
    int status = GrpcUnaryCall(KubeletSock(), "/v1beta1.Registration/Register",
                               EncodeRegisterRequest(opt_), &resp, &err);
    if (status != kOk) {
      fprintf(stderr, "[kgct-device-plugin] register failed (%d): %s\n",
              status, err.c_str());
      return false;
    }
    fprintf(stderr,
            "[kgct-device-plugin] registered %s with kubelet (%zu devices)\n",
            opt_.resource.c_str(), devices_.size());
    return true;
  }

  void Run() {
    server_.Listen(SocketPath());
    ino_t kubelet_ino = StatIno(KubeletSock());
    if (opt_.register_with_kubelet) {
      // Kubelet may not be up yet (DaemonSet races kubelet restarts): retry.
      for (int i = 0; i < 60 && !Register() && !g_stop; ++i) sleep(2);
    }
    time_t last_scan = time(nullptr);
    while (!g_stop) {
      server_.PollOnce(500);
      time_t now = time(nullptr);
      if (now - last_scan >= opt_.health_interval_s) {
        last_scan = now;
        RescanAndNotify();
        ino_t ino = StatIno(KubeletSock());
        if (opt_.register_with_kubelet && ino != 0 && ino != kubelet_ino) {
          fprintf(stderr,
                  "[kgct-device-plugin] kubelet.sock changed, re-registering\n");
          kubelet_ino = ino;
          Register();
        }
      }
      if (opt_.oneshot && pushed_once_ && NoLiveWatchers()) break;
    }
  }

 private:
  static ino_t StatIno(const std::string& path) {
    struct stat st{};
    return stat(path.c_str(), &st) == 0 ? st.st_ino : 0;
  }

  bool NoLiveWatchers() {
    Prune();
    return watchers_.empty();
  }

  void Prune() {
    watchers_.erase(std::remove_if(watchers_.begin(), watchers_.end(),
                                   [](const GrpcServer::StreamPtr& s) {
                                     return !s || !s->alive;
                                   }),
                    watchers_.end());
  }

  void RescanAndNotify() {
    auto fresh = ScanDevices(opt_);
    if (fresh == devices_) return;
    fprintf(stderr, "[kgct-device-plugin] device set changed: %zu devices\n",
            fresh.size());
    devices_ = std::move(fresh);
    Prune();
    std::string msg = EncodeListAndWatchResponse(devices_);
    for (auto& s : watchers_) server_.StreamSend(s, msg);
  }

  std::string Allocate(const std::string& req) {
    // AllocateRequest{ repeated ContainerAllocateRequest{repeated string=1} }
    PbWriter resp;
    PbReader r(req);
    while (r.Next()) {
      if (r.field() != 1) {
        r.skip();
        continue;
      }
      PbReader creq(r.bytes());
      std::vector<std::string> ids;
      while (creq.Next()) {
        if (creq.field() == 1)
          ids.emplace_back(creq.bytes());
        else
          creq.skip();
      }
      PbWriter cresp;
      std::string chips;
      for (const auto& id : ids) {
        if (!devices_.count(id))
          throw GrpcError(kNotFound, "unknown device " + id);
        // container_path mirrors host_path: jax/libtpu discover chips by
        // scanning /dev for the same names the host exposes.
        cresp.MessageField(
            3, EncodeDeviceSpec("/dev/" + id, opt_.dev_root + "/" + id, "rw"));
        std::string idx = id.substr(opt_.dev_prefix.size());
        chips += (chips.empty() ? "" : ",") + idx;
      }
      // libtpu chip selection (the TPU analogue of NVIDIA_VISIBLE_DEVICES).
      cresp.MessageField(1, EncodeEnvEntry("TPU_VISIBLE_CHIPS", chips));
      // vfio containers also need /dev/vfio when present on the host.
      struct stat st{};
      if (stat("/dev/vfio", &st) == 0)
        cresp.MessageField(2, EncodeMount("/dev/vfio", "/dev/vfio", false));
      resp.MessageField(1, cresp.str());
    }
    return resp.str();
  }

  Options opt_;
  GrpcServer server_;
  std::map<std::string, std::string> devices_;
  std::vector<GrpcServer::StreamPtr> watchers_;
  bool pushed_once_ = false;
};

int Main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto val = [&](const char* flag) -> const char* {
      size_t n = strlen(flag);
      if (a.rfind(flag, 0) == 0 && a.size() > n && a[n] == '=')
        return a.c_str() + n + 1;
      return nullptr;
    };
    if (const char* v = val("--plugin-dir")) opt.plugin_dir = v;
    else if (const char* v = val("--endpoint")) opt.endpoint = v;
    else if (const char* v = val("--resource")) opt.resource = v;
    else if (const char* v = val("--dev-root")) opt.dev_root = v;
    else if (const char* v = val("--dev-prefix")) opt.dev_prefix = v;
    else if (const char* v = val("--health-interval-s"))
      opt.health_interval_s = atoi(v);
    else if (const char* v = val("--write-cdi-spec")) opt.cdi_spec_path = v;
    else if (a == "--no-register") opt.register_with_kubelet = false;
    else if (a == "--oneshot") opt.oneshot = true;
    else {
      fprintf(stderr,
              "usage: kgct-tpu-device-plugin [--plugin-dir=DIR] "
              "[--endpoint=NAME.sock] [--resource=NAME] [--dev-root=DIR] "
              "[--dev-prefix=accel] [--health-interval-s=N] [--no-register] "
              "[--oneshot] [--write-cdi-spec=/etc/cdi/kgct-tpu.json]\n");
      return a == "--help" ? 0 : 2;
    }
  }
  if (!opt.cdi_spec_path.empty()) return WriteCdiSpec(opt, opt.cdi_spec_path);
  signal(SIGPIPE, SIG_IGN);
  signal(SIGTERM, OnSignal);
  signal(SIGINT, OnSignal);
  Plugin plugin(std::move(opt));
  plugin.Run();
  fprintf(stderr, "[kgct-device-plugin] exiting\n");
  return 0;
}

}  // namespace
}  // namespace kgct

int main(int argc, char** argv) { return kgct::Main(argc, argv); }
