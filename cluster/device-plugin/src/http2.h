// Minimal HTTP/2 (RFC 7540) connection — the subset gRPC-over-unix-socket
// needs, speaking to Go's net/http2 (kubelet) and gRPC C-core (tests).
//
// Covered: connection preface both roles, SETTINGS exchange + ack,
// HEADERS/CONTINUATION with padding + priority flags, DATA with padding,
// PING ack, RST_STREAM, GOAWAY, WINDOW_UPDATE with real send-side flow
// control (per-connection and per-stream windows; unsendable bytes queue and
// flush on window updates), receive-side window replenishment, and
// SETTINGS_MAX_FRAME_SIZE-bounded writes. Not covered (not needed, rejected
// or ignored): server push, priorities as scheduling input, TLS.
//
// Single-threaded: the owner runs a poll loop and calls OnReadable(); all
// callbacks fire on that thread. Writes are blocking (local unix sockets;
// peers are kubelet/CRI — they read promptly).
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "hpack.h"

namespace kgct {

struct Http2Error : std::runtime_error {
  using std::runtime_error::runtime_error;
};

class Http2Conn {
 public:
  struct Events {
    // end_stream: no DATA will follow (trailers-only or final frame).
    std::function<void(uint32_t stream, std::vector<Header>, bool end_stream)>
        on_headers;
    std::function<void(uint32_t stream, const std::string&, bool end_stream)>
        on_data;
    std::function<void(uint32_t stream)> on_rst_stream;
    std::function<void()> on_goaway;
  };

  enum class Role { kClient, kServer };

  Http2Conn(int fd, Role role, Events events);

  // Sends our preface/SETTINGS. Call once before the poll loop.
  void Handshake();

  // Feed incoming bytes from the socket. Returns false when the peer closed
  // the connection. Throws Http2Error on protocol violations (caller should
  // close). Callbacks fire from inside.
  bool OnReadable();

  void SendHeaders(uint32_t stream, const std::vector<Header>& headers,
                   bool end_stream);
  // Queues if flow-control windows are exhausted; flushed on WINDOW_UPDATE.
  void SendData(uint32_t stream, const std::string& payload, bool end_stream);
  void SendRstStream(uint32_t stream, uint32_t error_code);
  void SendGoAway(uint32_t error_code);

  // Client role: next available (odd) stream id.
  uint32_t NextStreamId();

  int fd() const { return fd_; }

 private:
  struct Stream {
    int64_t send_window = 65535;
    std::string pending;     // bytes waiting for window
    bool pending_end = false;
    bool closed_local = false;
  };

  Stream& GetStream(uint32_t id);
  void WriteAll(const void* p, size_t n);
  void WriteFrame(uint8_t type, uint8_t flags, uint32_t stream,
                  const std::string& payload);
  void HandleFrame(uint8_t type, uint8_t flags, uint32_t stream,
                   const uint8_t* p, size_t n);
  void HandleSettings(uint8_t flags, const uint8_t* p, size_t n);
  void FlushPending(uint32_t stream);
  void TrySend(uint32_t stream, Stream& st);

  int fd_;
  Role role_;
  Events events_;
  std::string inbuf_;
  bool preface_seen_ = false;  // server role: client preface
  HpackDecoder hpack_in_;

  // Header block accumulation across HEADERS + CONTINUATION frames.
  uint32_t continuation_stream_ = 0;
  std::string header_block_;
  bool header_end_stream_ = false;
  bool in_continuation_ = false;

  int64_t conn_send_window_ = 65535;
  uint32_t peer_max_frame_ = 16384;
  uint32_t peer_initial_window_ = 65535;
  std::map<uint32_t, Stream> streams_;
  uint32_t next_stream_id_ = 1;
};

}  // namespace kgct
