#include "grpc.h"

#include <errno.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>

namespace kgct {
namespace {

std::string MessageFrame(const std::string& payload) {
  std::string out(5, '\0');
  uint32_t n = static_cast<uint32_t>(payload.size());
  out[0] = 0;  // uncompressed
  out[1] = char(n >> 24), out[2] = char(n >> 16);
  out[3] = char(n >> 8), out[4] = char(n);
  return out + payload;
}

// Extracts the first complete message from a DATA accumulation buffer.
// Returns false if incomplete. Throws GrpcError on a compressed message.
bool PopMessage(std::string* buf, std::string* msg) {
  if (buf->size() < 5) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf->data());
  if (p[0] != 0)
    throw GrpcError(kUnimplemented, "compressed grpc messages unsupported");
  uint32_t n = (uint32_t(p[1]) << 24) | (uint32_t(p[2]) << 16) |
               (uint32_t(p[3]) << 8) | uint32_t(p[4]);
  if (buf->size() < 5 + size_t(n)) return false;
  msg->assign(*buf, 5, n);
  buf->erase(0, 5 + size_t(n));
  return true;
}

int UnixConnect(const std::string& path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (path.size() >= sizeof(addr.sun_path)) {
    ::close(fd);
    errno = ENAMETOOLONG;
    return -1;
  }
  memcpy(addr.sun_path, path.c_str(), path.size() + 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

}  // namespace

// ---------------------------------------------------------------------------
// Server

struct GrpcServer::Conn {
  explicit Conn(int fd) : fd(fd) {}
  int fd;
  std::unique_ptr<Http2Conn> h2;
  struct Call {
    std::string path;
    std::string data;      // accumulated request DATA bytes
    bool headers_seen = false;
  };
  std::map<uint32_t, Call> calls;
  std::map<uint32_t, StreamPtr> live_streams;
  bool dead = false;
};

GrpcServer::GrpcServer() = default;

GrpcServer::~GrpcServer() {
  for (auto& c : conns_) {
    for (auto& [sid, sp] : c->live_streams) sp->alive = false;
    ::close(c->fd);
  }
  if (listen_fd_ >= 0) ::close(listen_fd_);
  if (!socket_path_.empty()) ::unlink(socket_path_.c_str());
}

void GrpcServer::AddUnary(const std::string& path, UnaryFn fn) {
  unary_[path] = std::move(fn);
}

void GrpcServer::AddServerStream(const std::string& path, StreamStartFn fn) {
  streams_[path] = std::move(fn);
}

void GrpcServer::Listen(const std::string& unix_path) {
  socket_path_ = unix_path;
  ::unlink(unix_path.c_str());
  listen_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (listen_fd_ < 0) throw Http2Error("socket() failed");
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  if (unix_path.size() >= sizeof(addr.sun_path))
    throw Http2Error("socket path too long");
  memcpy(addr.sun_path, unix_path.c_str(), unix_path.size() + 1);
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
    throw Http2Error(std::string("bind: ") + strerror(errno));
  if (::listen(listen_fd_, 16) < 0)
    throw Http2Error(std::string("listen: ") + strerror(errno));
}

void GrpcServer::Accept() {
  int fd = ::accept(listen_fd_, nullptr, nullptr);
  if (fd < 0) return;
  auto conn = std::make_unique<Conn>(fd);
  Conn* c = conn.get();
  Http2Conn::Events ev;
  ev.on_headers = [this, c](uint32_t stream, std::vector<Header> hdrs,
                            bool end_stream) {
    auto& call = c->calls[stream];
    if (!call.headers_seen) {
      call.headers_seen = true;
      for (const auto& h : hdrs)
        if (h.name == ":path") call.path = h.value;
    }
    if (end_stream) Dispatch(c, stream);
  };
  ev.on_data = [this, c](uint32_t stream, const std::string& data,
                         bool end_stream) {
    auto it = c->calls.find(stream);
    if (it == c->calls.end()) return;
    it->second.data += data;
    if (end_stream) Dispatch(c, stream);
  };
  ev.on_rst_stream = [c](uint32_t stream) {
    auto it = c->live_streams.find(stream);
    if (it != c->live_streams.end()) {
      it->second->alive = false;
      c->live_streams.erase(it);
    }
    c->calls.erase(stream);
  };
  ev.on_goaway = [c]() { c->dead = true; };
  c->h2 = std::make_unique<Http2Conn>(fd, Http2Conn::Role::kServer, ev);
  c->h2->Handshake();
  conns_.push_back(std::move(conn));
}

void GrpcServer::Dispatch(Conn* c, uint32_t stream) {
  auto it = c->calls.find(stream);
  if (it == c->calls.end()) return;
  Conn::Call call = std::move(it->second);
  c->calls.erase(it);

  std::string req;
  int status = kOk;
  std::string message;
  try {
    PopMessage(&call.data, &req);  // empty request body is a valid Empty
  } catch (const GrpcError& e) {
    status = e.code;
    message = e.what();
  }

  if (status == kOk) {
    if (auto u = unary_.find(call.path); u != unary_.end()) {
      try {
        std::string resp = u->second(req);
        c->h2->SendHeaders(stream,
                           {{":status", "200"},
                            {"content-type", "application/grpc"}},
                           false);
        c->h2->SendData(stream, MessageFrame(resp), false);
        c->h2->SendHeaders(stream, {{"grpc-status", "0"}}, true);
        return;
      } catch (const GrpcError& e) {
        status = e.code;
        message = e.what();
      } catch (const std::exception& e) {
        status = kInternal;
        message = e.what();
      }
    } else if (auto s = streams_.find(call.path); s != streams_.end()) {
      auto handle = std::make_shared<StreamHandle>();
      handle->alive = true;
      handle->conn = c->h2.get();
      handle->stream = stream;
      c->live_streams[stream] = handle;
      c->h2->SendHeaders(stream,
                         {{":status", "200"},
                          {"content-type", "application/grpc"}},
                         false);
      try {
        s->second(req, handle);
        return;
      } catch (const std::exception& e) {
        c->live_streams.erase(stream);
        handle->alive = false;
        c->h2->SendHeaders(stream,
                           {{"grpc-status", std::to_string(kInternal)},
                            {"grpc-message", e.what()}},
                           true);
        return;
      }
    } else {
      status = kUnimplemented;
      message = "unknown method " + call.path;
    }
  }
  // Trailers-only error response.
  c->h2->SendHeaders(stream,
                     {{":status", "200"},
                      {"content-type", "application/grpc"},
                      {"grpc-status", std::to_string(status)},
                      {"grpc-message", message}},
                     true);
}

void GrpcServer::StreamSend(const StreamPtr& s, const std::string& message) {
  if (!s || !s->alive) return;
  s->conn->SendData(s->stream, MessageFrame(message), false);
}

void GrpcServer::StreamClose(const StreamPtr& s, int status,
                             const std::string& msg) {
  if (!s || !s->alive) return;
  s->alive = false;
  std::vector<Header> trailers = {{"grpc-status", std::to_string(status)}};
  if (!msg.empty()) trailers.push_back({"grpc-message", msg});
  s->conn->SendHeaders(s->stream, trailers, true);
}

void GrpcServer::CloseConn(Conn* c) {
  for (auto& [sid, sp] : c->live_streams) sp->alive = false;
  c->live_streams.clear();
  ::close(c->fd);
  c->fd = -1;
  c->dead = true;
}

void GrpcServer::PollOnce(int timeout_ms) {
  std::vector<pollfd> fds;
  fds.push_back({listen_fd_, POLLIN, 0});
  for (auto& c : conns_)
    if (!c->dead) fds.push_back({c->fd, POLLIN, 0});
  int n = ::poll(fds.data(), fds.size(), timeout_ms);
  if (n <= 0) return;
  if (fds[0].revents & POLLIN) Accept();
  size_t fi = 1;
  for (auto& c : conns_) {
    if (c->dead) continue;
    if (fi >= fds.size()) break;
    pollfd& pfd = fds[fi++];
    if (pfd.fd != c->fd) continue;  // conns_ mutated by Accept: resync next tick
    if (pfd.revents & (POLLIN | POLLHUP | POLLERR)) {
      try {
        if (!c->h2->OnReadable()) CloseConn(c.get());
      } catch (const std::exception& e) {
        fprintf(stderr, "[kgct-device-plugin] conn error: %s\n", e.what());
        CloseConn(c.get());
      }
    }
  }
  conns_.erase(std::remove_if(conns_.begin(), conns_.end(),
                              [](const std::unique_ptr<Conn>& c) {
                                return c->dead;
                              }),
               conns_.end());
}

// ---------------------------------------------------------------------------
// Client (registration)

int GrpcUnaryCall(const std::string& unix_path, const std::string& method_path,
                  const std::string& request, std::string* response,
                  std::string* error, int timeout_ms) {
  int fd = UnixConnect(unix_path);
  if (fd < 0) {
    *error = "connect " + unix_path + ": " + strerror(errno);
    return kUnavailable;
  }

  int grpc_status = -1;
  std::string grpc_message;
  std::string body;
  bool done = false;

  Http2Conn::Events ev;
  ev.on_headers = [&](uint32_t /*stream*/, std::vector<Header> hdrs,
                      bool end_stream) {
    for (const auto& h : hdrs) {
      if (h.name == "grpc-status") grpc_status = atoi(h.value.c_str());
      if (h.name == "grpc-message") grpc_message = h.value;
    }
    if (end_stream) done = true;
  };
  ev.on_data = [&](uint32_t /*stream*/, const std::string& data,
                   bool end_stream) {
    body += data;
    if (end_stream) done = true;
  };
  ev.on_rst_stream = [&](uint32_t) { done = true; };
  ev.on_goaway = [&]() { done = true; };

  try {
    Http2Conn h2(fd, Http2Conn::Role::kClient, ev);
    h2.Handshake();
    uint32_t stream = h2.NextStreamId();
    h2.SendHeaders(stream,
                   {{":method", "POST"},
                    {":scheme", "http"},
                    {":path", method_path},
                    {":authority", "localhost"},
                    {"content-type", "application/grpc"},
                    {"user-agent", "kgct-tpu-device-plugin/1.0"},
                    {"te", "trailers"}},
                   false);
    h2.SendData(stream, MessageFrame(request), true);

    pollfd pfd{fd, POLLIN, 0};
    int waited = 0;
    while (!done && waited < timeout_ms) {
      int r = ::poll(&pfd, 1, 100);
      waited += 100;
      if (r < 0 && errno != EINTR) break;
      if (r > 0 && !h2.OnReadable()) break;
    }
  } catch (const std::exception& e) {
    ::close(fd);
    *error = e.what();
    return kInternal;
  }
  ::close(fd);

  if (!done && grpc_status < 0) {
    *error = "timeout waiting for " + method_path;
    return kUnavailable;
  }
  if (grpc_status != 0) {
    *error = grpc_message.empty() ? "grpc status " + std::to_string(grpc_status)
                                  : grpc_message;
    return grpc_status < 0 ? kUnknown : grpc_status;
  }
  std::string msg;
  if (PopMessage(&body, &msg)) *response = msg;
  return kOk;
}

}  // namespace kgct
