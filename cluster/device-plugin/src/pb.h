// Minimal protobuf wire-format codec (proto3 subset: varint, length-
// delimited). The kubelet device-plugin API v1beta1 uses only strings,
// bools, int64s, repeated messages, and map<string,string> — all expressible
// with these two wire types. Hand-rolled instead of linking libprotobuf so
// the plugin binary has zero dependencies beyond libc/libstdc++ (it runs in
// a scratch container on every node).
//
// Wire-format correctness is proven in tests/test_device_plugin.py: the fake
// kubelet serializes with the real libprotobuf (protoc-generated classes)
// and the plugin's responses are deserialized by it.
#pragma once

#include <cstdint>
#include <stdexcept>
#include <string>
#include <string_view>

namespace kgct {

struct PbError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

class PbWriter {
 public:
  void VarintField(int field, uint64_t v) {
    Key(field, 0);
    Varint(v);
  }
  void BoolField(int field, bool v) {
    if (v) VarintField(field, 1);  // proto3: default values are omitted
  }
  void StringField(int field, std::string_view s) {
    if (s.empty()) return;
    BytesField(field, s);
  }
  // Always emitted (submessages may be meaningfully empty).
  void MessageField(int field, std::string_view bytes) { BytesField(field, bytes); }

  const std::string& str() const { return out_; }

 private:
  void BytesField(int field, std::string_view s) {
    Key(field, 2);
    Varint(s.size());
    out_.append(s);
  }
  void Key(int field, int wire_type) {
    Varint((static_cast<uint64_t>(field) << 3) | wire_type);
  }
  void Varint(uint64_t v) {
    while (v >= 0x80) {
      out_.push_back(static_cast<char>(0x80 | (v & 0x7f)));
      v >>= 7;
    }
    out_.push_back(static_cast<char>(v));
  }
  std::string out_;
};

class PbReader {
 public:
  explicit PbReader(std::string_view data) : p_(data.data()), end_(p_ + data.size()) {}

  // Advances to the next field; false at end of message.
  bool Next() {
    if (p_ >= end_) return false;
    uint64_t key = Varint();
    field_ = static_cast<int>(key >> 3);
    wire_ = static_cast<int>(key & 7);
    return true;
  }
  int field() const { return field_; }

  uint64_t varint() {
    if (wire_ != 0) throw PbError("pb: expected varint");
    return Varint();
  }
  std::string_view bytes() {
    if (wire_ != 2) throw PbError("pb: expected length-delimited");
    uint64_t n = Varint();
    if (static_cast<uint64_t>(end_ - p_) < n) throw PbError("pb: truncated");
    std::string_view s(p_, n);
    p_ += n;
    return s;
  }
  void skip() {
    switch (wire_) {
      case 0: Varint(); break;
      case 1: Advance(8); break;
      case 2: bytes(); break;
      case 5: Advance(4); break;
      default: throw PbError("pb: unsupported wire type");
    }
  }

 private:
  void Advance(size_t n) {
    if (static_cast<size_t>(end_ - p_) < n) throw PbError("pb: truncated");
    p_ += n;
  }
  uint64_t Varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p_ < end_) {
      uint8_t b = static_cast<uint8_t>(*p_++);
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift >= 64) break;
    }
    throw PbError("pb: bad varint");
  }

  const char* p_;
  const char* end_;
  int field_ = 0;
  int wire_ = 0;
};

}  // namespace kgct
