// HPACK (RFC 7541) header codec — the subset a kubelet device plugin needs.
//
// Decoder: complete — indexed fields, all literal forms, dynamic-table
// inserts/evictions/size updates, and full static-Huffman string decoding
// (Go's and gRPC C-core's encoders Huffman-compress almost every literal, so
// a plugin cannot interop without it). Malformed input throws HpackError and
// the connection is torn down — never a silent mis-parse.
//
// Encoder: deliberately minimal and stateless — exact static-table matches
// are sent indexed, everything else as literal-without-indexing with raw
// (H=0) strings. Both are always legal; peers do not need our encoder to use
// the dynamic table or Huffman.
//
// TPU-native framework note: this file replaces the role the NVIDIA device
// plugin's vendored gRPC stack played in the reference's GPU enablement layer
// (reference gpu-crio-setup.sh:87-126, old_README.md:1206-1318).
#pragma once

#include <cstddef>
#include <cstdint>
#include <deque>
#include <stdexcept>
#include <string>
#include <vector>

namespace kgct {

struct Header {
  std::string name;
  std::string value;
};

struct HpackError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Decodes one complete header block (HEADERS + CONTINUATIONs payload).
class HpackDecoder {
 public:
  std::vector<Header> Decode(const uint8_t* p, size_t n);

 private:
  const Header& Lookup(uint64_t index) const;
  void Insert(Header h);

  size_t max_size_ = 4096;  // peer may lower/raise via table-size updates
  size_t size_ = 0;
  std::deque<Header> dynamic_;  // front = most recent (index 62)
};

std::string HpackEncode(const std::vector<Header>& headers);

// Exposed for tests: RFC 7541 static Huffman decode of a complete string.
std::string HuffmanDecode(const uint8_t* p, size_t n);

}  // namespace kgct
