#include "hpack.h"

#include <array>
#include <memory>

#include "hpack_huffman_table.h"

namespace kgct {
namespace {

// RFC 7541 Appendix A: the 61-entry static table.
const std::array<Header, 62> kStatic = {{
    {"", ""},  // index 0 unused
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
}};

// Binary trie for Huffman decode, built once. Node 0 is the root; children
// index further nodes; sym >= 0 marks a leaf.
struct HuffNode {
  int32_t child[2] = {-1, -1};
  int32_t sym = -1;
};

const std::vector<HuffNode>& HuffTrie() {
  static const std::vector<HuffNode>* trie = [] {
    auto* t = new std::vector<HuffNode>(1);
    for (int s = 0; s < 257; ++s) {
      uint32_t bits = kHuffSyms[s].bits;
      int len = kHuffSyms[s].len;
      size_t node = 0;
      for (int i = len - 1; i >= 0; --i) {
        int b = (bits >> i) & 1;
        if ((*t)[node].child[b] < 0) {
          (*t)[node].child[b] = static_cast<int32_t>(t->size());
          t->emplace_back();
        }
        node = (*t)[node].child[b];
      }
      (*t)[node].sym = s;
    }
    return t;
  }();
  return *trie;
}

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), end_(p + n) {}
  bool Done() const { return p_ >= end_; }
  uint8_t Peek() const {
    if (Done()) throw HpackError("hpack: truncated block");
    return *p_;
  }
  uint8_t Next() {
    uint8_t b = Peek();
    ++p_;
    return b;
  }
  // RFC 7541 §5.1 integer with an N-bit prefix (prefix taken from Next()).
  uint64_t Int(int prefix_bits) {
    uint8_t mask = static_cast<uint8_t>((1u << prefix_bits) - 1);
    uint64_t v = Next() & mask;
    if (v < mask) return v;
    int shift = 0;
    while (true) {
      uint8_t b = Next();
      v += static_cast<uint64_t>(b & 0x7f) << shift;
      shift += 7;
      if (!(b & 0x80)) return v;
      if (shift > 56) throw HpackError("hpack: integer overflow");
    }
  }
  std::string String() {
    bool huffman = Peek() & 0x80;
    uint64_t len = Int(7);
    if (static_cast<size_t>(end_ - p_) < len)
      throw HpackError("hpack: truncated string");
    const uint8_t* s = p_;
    p_ += len;
    if (!huffman) return std::string(reinterpret_cast<const char*>(s), len);
    return HuffmanDecode(s, len);
  }

 private:
  const uint8_t* p_;
  const uint8_t* end_;
};

}  // namespace

std::string HuffmanDecode(const uint8_t* p, size_t n) {
  const auto& trie = HuffTrie();
  std::string out;
  size_t node = 0;
  int depth = 0;           // bits consumed since last symbol
  bool pad_ones = true;    // all such bits were 1s (valid EOS-prefix padding)
  for (size_t i = 0; i < n; ++i) {
    for (int bit = 7; bit >= 0; --bit) {
      int b = (p[i] >> bit) & 1;
      int32_t next = trie[node].child[b];
      if (next < 0) throw HpackError("hpack: invalid huffman code");
      node = static_cast<size_t>(next);
      ++depth;
      pad_ones = pad_ones && b == 1;
      if (trie[node].sym >= 0) {
        if (trie[node].sym == 256)
          throw HpackError("hpack: unexpected EOS symbol");
        out.push_back(static_cast<char>(trie[node].sym));
        node = 0;
        depth = 0;
        pad_ones = true;
      }
    }
  }
  // Remaining bits must be a strict prefix of EOS: fewer than 8 bits, all 1s.
  if (depth >= 8 || !pad_ones) throw HpackError("hpack: bad padding");
  return out;
}

const Header& HpackDecoder::Lookup(uint64_t index) const {
  if (index == 0) throw HpackError("hpack: index 0");
  if (index <= 61) return kStatic[index];
  size_t d = index - 62;
  if (d >= dynamic_.size()) throw HpackError("hpack: index out of range");
  return dynamic_[d];
}

void HpackDecoder::Insert(Header h) {
  size_t entry = h.name.size() + h.value.size() + 32;
  dynamic_.push_front(std::move(h));
  size_ += entry;
  while (size_ > max_size_ && !dynamic_.empty()) {
    size_ -= dynamic_.back().name.size() + dynamic_.back().value.size() + 32;
    dynamic_.pop_back();
  }
  if (size_ > max_size_) {  // single entry larger than the table: empty it
    dynamic_.clear();
    size_ = 0;
  }
}

std::vector<Header> HpackDecoder::Decode(const uint8_t* p, size_t n) {
  Reader r(p, n);
  std::vector<Header> out;
  while (!r.Done()) {
    uint8_t b = r.Peek();
    if (b & 0x80) {  // indexed field
      out.push_back(Lookup(r.Int(7)));
    } else if (b & 0x40) {  // literal, incremental indexing
      uint64_t idx = r.Int(6);
      Header h;
      h.name = idx ? Lookup(idx).name : r.String();
      h.value = r.String();
      out.push_back(h);
      Insert(std::move(h));
    } else if (b & 0x20) {  // dynamic table size update
      uint64_t sz = r.Int(5);
      // Peers may shrink below or (back) up to the SETTINGS value; we never
      // advertise a custom limit so cap at the default.
      if (sz > 4096) throw HpackError("hpack: size update above limit");
      max_size_ = sz;
      while (size_ > max_size_ && !dynamic_.empty()) {
        size_ -= dynamic_.back().name.size() +
                 dynamic_.back().value.size() + 32;
        dynamic_.pop_back();
      }
    } else {  // literal, no indexing (0000) / never indexed (0001)
      uint64_t idx = r.Int(4);
      Header h;
      h.name = idx ? Lookup(idx).name : r.String();
      h.value = r.String();
      out.push_back(std::move(h));
    }
  }
  return out;
}

namespace {

void EncodeInt(std::string* out, uint64_t v, int prefix_bits, uint8_t flags) {
  uint8_t mask = static_cast<uint8_t>((1u << prefix_bits) - 1);
  if (v < mask) {
    out->push_back(static_cast<char>(flags | v));
    return;
  }
  out->push_back(static_cast<char>(flags | mask));
  v -= mask;
  while (v >= 128) {
    out->push_back(static_cast<char>(0x80 | (v & 0x7f)));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

void EncodeString(std::string* out, const std::string& s) {
  EncodeInt(out, s.size(), 7, 0x00);  // H=0: raw
  out->append(s);
}

}  // namespace

std::string HpackEncode(const std::vector<Header>& headers) {
  std::string out;
  for (const auto& h : headers) {
    int exact = -1, name_only = -1;
    for (int i = 1; i <= 61; ++i) {
      if (kStatic[i].name != h.name) continue;
      if (name_only < 0) name_only = i;
      if (kStatic[i].value == h.value) {
        exact = i;
        break;
      }
    }
    if (exact > 0) {
      EncodeInt(&out, static_cast<uint64_t>(exact), 7, 0x80);
    } else if (name_only > 0) {
      EncodeInt(&out, static_cast<uint64_t>(name_only), 4, 0x00);
      EncodeString(&out, h.value);
    } else {
      EncodeInt(&out, 0, 4, 0x00);
      EncodeString(&out, h.name);
      EncodeString(&out, h.value);
    }
  }
  return out;
}

}  // namespace kgct
