#!/usr/bin/env bash
# ha_setup.sh — HA control plane: keepalived VRRP VIP + haproxy apiserver LB.
#
# Completes the CONTROL_PLANE_ENDPOINT path of tpu_node_setup.sh with the
# reference's multi-control-plane recipe (reference multi-cp.md:196-291),
# templated instead of hand-edited: keepalived holds a virtual IP on the
# healthiest control-plane node (VRRP, apiserver healthz tracked), haproxy
# round-robins TCP :<port> across every apiserver with TLS healthz checks.
#
# Run on EACH control-plane node, then init the first one through the VIP:
#   sudo bash ha_setup.sh --vip=10.0.0.250 --cp-ips=10.0.0.1,10.0.0.2,10.0.0.3 \
#        --interface=ens3 --state=MASTER --priority=101
#   CONTROL_PLANE_ENDPOINT=10.0.0.250:8443 \
#        sudo bash tpu_node_setup.sh --yes --role=control_plane
#   (remaining CPs: --state=BACKUP --priority=100,99 + kubeadm join --control-plane)
#
# DRY_RUN=1 prints the rendered configs without touching the system.
set -euo pipefail

VIP=""
INTERFACE="${INTERFACE:-eth0}"
STATE="${STATE:-MASTER}"           # MASTER on one node, BACKUP elsewhere
PRIORITY="${PRIORITY:-101}"        # highest wins the VIP
VRID="${VRID:-51}"
CP_IPS=""                          # comma-separated apiserver IPs
LB_PORT="${LB_PORT:-8443}"         # haproxy bind (8443: co-located with
                                   # apiserver:6443 on the same nodes)
API_PORT="${API_PORT:-6443}"
AUTH_PASS="${AUTH_PASS:-}"         # VRRP auth; generated if empty
DRY_RUN="${DRY_RUN:-0}"

log()  { echo -e "\e[32m[ha-setup]\e[0m $*"; }
err()  { echo -e "\e[31m[ha-setup]\e[0m $*" >&2; }
run()  { if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: $*"; else "$@"; fi }

usage() { grep '^#' "$0" | head -20; exit 1; }

for arg in "$@"; do
  case "$arg" in
    --vip=*) VIP="${arg#*=}" ;;
    --interface=*) INTERFACE="${arg#*=}" ;;
    --state=*) STATE="${arg#*=}" ;;
    --priority=*) PRIORITY="${arg#*=}" ;;
    --vrid=*) VRID="${arg#*=}" ;;
    --cp-ips=*) CP_IPS="${arg#*=}" ;;
    --lb-port=*) LB_PORT="${arg#*=}" ;;
    --api-port=*) API_PORT="${arg#*=}" ;;
    --help|-h) usage ;;
    *) err "unknown flag: $arg"; usage ;;
  esac
done

[[ -z "$VIP" ]] && { err "--vip=<virtual ip> required"; exit 1; }
[[ -z "$CP_IPS" ]] && { err "--cp-ips=<ip1,ip2,...> required"; exit 1; }
[[ "$STATE" == "MASTER" || "$STATE" == "BACKUP" ]] \
  || { err "--state must be MASTER or BACKUP"; exit 1; }
if [[ -z "$AUTH_PASS" ]]; then
  # VRRP auth_pass uses only the first 8 chars; random beats the reference's
  # hardcoded literal (multi-cp.md:257).
  AUTH_PASS=$(head -c6 /dev/urandom | base64 | tr -dc 'a-zA-Z0-9' | head -c8)
  log "generated VRRP auth_pass (must MATCH on all control-plane nodes: " \
      "pass AUTH_PASS=... explicitly)"
fi

render_haproxy() {  # reference multi-cp.md:196-238
  cat <<EOF
global
    log stdout format raw local0
    daemon

defaults
    log     global
    mode    tcp
    option  tcplog
    timeout connect 5s
    timeout client  30s
    timeout server  30s

frontend apiserver
    bind *:$LB_PORT
    mode tcp
    option tcplog
    default_backend apiserverbackend

backend apiserverbackend
    option httpchk
    http-check connect ssl
    http-check send meth GET uri /healthz
    http-check expect status 200
    mode tcp
    balance roundrobin
EOF
  local i=1
  for ip in ${CP_IPS//,/ }; do
    echo "    server cp$i $ip:$API_PORT check verify none"
    i=$((i+1))
  done
}

render_keepalived() {  # reference multi-cp.md:240-269
  cat <<EOF
global_defs {
    router_id kgct_ha
}
vrrp_script check_apiserver {
    script "/etc/keepalived/check_apiserver.sh"
    interval 3
    weight -2
    fall 10
    rise 2
}

vrrp_instance VI_1 {
    state $STATE
    interface $INTERFACE
    virtual_router_id $VRID
    priority $PRIORITY
    authentication {
        auth_type PASS
        auth_pass $AUTH_PASS
    }
    virtual_ipaddress {
        $VIP
    }
    track_script {
        check_apiserver
    }
}
EOF
}

render_check() {  # reference multi-cp.md:275-285
  cat <<EOF
#!/bin/sh
# keepalived health probe: drop VRRP priority when the local apiserver
# (or, on the VIP holder, the VIP-routed apiserver) stops answering healthz.
errorExit() { echo "*** \$*" 1>&2; exit 1; }
curl -sfk --max-time 2 https://localhost:$API_PORT/healthz -o /dev/null \\
  || errorExit "Error GET https://localhost:$API_PORT/healthz"
if ip addr | grep -q "$VIP"; then
  curl -sfk --max-time 2 https://$VIP:$LB_PORT/healthz -o /dev/null \\
    || errorExit "Error GET https://$VIP:$LB_PORT/healthz"
fi
EOF
}

main() {
  log "HA control plane: VIP=$VIP state=$STATE priority=$PRIORITY lb=:$LB_PORT"
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: apt-get install -y keepalived haproxy"
    echo "=== /etc/haproxy/haproxy.cfg ==="
    render_haproxy
    echo "=== /etc/keepalived/keepalived.conf ==="
    render_keepalived
    echo "=== /etc/keepalived/check_apiserver.sh ==="
    render_check
    echo "DRY: systemctl enable --now haproxy keepalived"
    log "init via VIP: CONTROL_PLANE_ENDPOINT=$VIP:$LB_PORT tpu_node_setup.sh --role=control_plane"
    return 0
  fi
  apt-get install -y keepalived haproxy
  render_haproxy > /etc/haproxy/haproxy.cfg
  mkdir -p /etc/keepalived
  render_keepalived > /etc/keepalived/keepalived.conf
  render_check > /etc/keepalived/check_apiserver.sh
  chmod +x /etc/keepalived/check_apiserver.sh
  systemctl enable --now haproxy
  systemctl restart haproxy
  systemctl enable --now keepalived
  systemctl restart keepalived
  log "HA stack up. Initialize the FIRST control plane with:"
  log "  CONTROL_PLANE_ENDPOINT=$VIP:$LB_PORT sudo bash tpu_node_setup.sh --yes --role=control_plane"
  log "Join further control planes with the --control-plane join command"
  log "from 'kubeadm init' output (certs uploaded via --upload-certs)."
}

main
