#!/usr/bin/env bash
# runtime_setup.sh — container runtime install for TPU VM nodes.
#
# Role of the reference's crio_setup.sh (pinned CRI-O v1.33 + crictl +
# proxy drop-in, reference crio_setup.sh:1-70). TPU VM images ship containerd;
# this script installs/pins it where absent, installs crictl for CRI
# debugging, and wires the proxy drop-in. CRI-O remains selectable for parity
# (--runtime=crio) since the engine layer is runtime-agnostic via CRI_SOCKET.
#
# Usage: sudo bash runtime_setup.sh [--runtime=containerd|crio]
#        DRY_RUN=1 bash runtime_setup.sh
set -euo pipefail

RUNTIME="${RUNTIME:-containerd}"
CRICTL_VERSION="${CRICTL_VERSION:-v1.33.0}"   # pinned (reference crio_setup.sh:46)
CRIO_VERSION="${CRIO_VERSION:-v1.33}"         # pinned (reference crio_setup.sh:5-6)
HTTP_PROXY_URL="${HTTP_PROXY_URL:-}"
DRY_RUN="${DRY_RUN:-0}"

log()  { echo -e "\e[32m[runtime]\e[0m $*"; }
err()  { echo -e "\e[31m[runtime]\e[0m $*" >&2; }
run()  { if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: $*"; else "$@"; fi }

for arg in "$@"; do
  case "$arg" in
    --runtime=*) RUNTIME="${arg#*=}" ;;
    *) err "unknown flag $arg"; exit 1 ;;
  esac
done

apt_proxied() {  # apt through the egress proxy (reference crio_setup.sh:27-31)
  if [[ -n "$HTTP_PROXY_URL" ]]; then
    run apt-get -o "Acquire::http::Proxy=$HTTP_PROXY_URL" \
                -o "Acquire::https::Proxy=$HTTP_PROXY_URL" "$@"
  else
    run apt-get "$@"
  fi
}

install_containerd() {
  if command -v containerd >/dev/null; then
    log "containerd already present: $(containerd --version 2>/dev/null || true)"
  else
    log "installing containerd"
    apt_proxied update
    apt_proxied install -y containerd
  fi
  run systemctl enable --now containerd
}

install_crio() {  # parity path (reference crio_setup.sh:19-41)
  log "installing CRI-O $CRIO_VERSION"
  if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: add opensuse repo + install cri-o"; return; fi
  local keyring=/etc/apt/keyrings/cri-o-apt-keyring.gpg
  mkdir -p /etc/apt/keyrings
  local curl_cmd=(curl -fsSL)
  [[ -n "$HTTP_PROXY_URL" ]] && curl_cmd+=(--proxy "$HTTP_PROXY_URL")
  "${curl_cmd[@]}" \
    "https://download.opensuse.org/repositories/isv:/cri-o:/stable:/$CRIO_VERSION/deb/Release.key" \
    | gpg --dearmor -o "$keyring"
  echo "deb [signed-by=$keyring] https://download.opensuse.org/repositories/isv:/cri-o:/stable:/$CRIO_VERSION/deb/ /" \
    > /etc/apt/sources.list.d/cri-o.list
  apt_proxied update
  apt_proxied install -y cri-o
  systemctl enable --now crio
}

install_crictl() {  # CRI debugging CLI (reference crio_setup.sh:46-54)
  command -v crictl >/dev/null && { log "crictl present"; return; }
  log "installing crictl $CRICTL_VERSION"
  if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: download crictl"; return; fi
  local url="https://github.com/kubernetes-sigs/cri-tools/releases/download/$CRICTL_VERSION/crictl-$CRICTL_VERSION-linux-amd64.tar.gz"
  local curl_cmd=(curl -fsSL)
  [[ -n "$HTTP_PROXY_URL" ]] && curl_cmd+=(--proxy "$HTTP_PROXY_URL")
  "${curl_cmd[@]}" "$url" | tar -C /usr/local/bin -xz crictl
  local sock="unix:///run/containerd/containerd.sock"
  [[ "$RUNTIME" == "crio" ]] && sock="unix:///var/run/crio/crio.sock"
  cat > /etc/crictl.yaml <<EOF
runtime-endpoint: $sock
image-endpoint: $sock
EOF
}

install_crun_from_source() {
  # CRI-O parity for the reference's deepest runtime fix: distro crun broke
  # containers ("unknown version specified", reference old_README.md:1184-1199),
  # so v1.21 was compiled from C source (reference gpu-crio-setup.sh:43-56).
  # Gated: only needed with --runtime=crio when the packaged crun misbehaves.
  [[ "${BUILD_CRUN:-0}" != "1" ]] && return 0
  local ver="${CRUN_VERSION:-1.21}"
  log "building crun $ver from source"
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: apt install build deps (autoconf libtool libcap-dev libseccomp-dev libyajl-dev)"
    echo "DRY: git clone --branch $ver https://github.com/containers/crun && autogen/configure/make install"
    return 0
  fi
  apt_proxied install -y make gcc git autoconf automake libtool pkg-config \
    python3 libcap-dev libseccomp-dev libyajl-dev go-md2man
  local src=/usr/local/src/crun
  rm -rf "$src"
  git clone --depth 1 --branch "$ver" https://github.com/containers/crun "$src"
  (cd "$src" && ./autogen.sh && ./configure && make -j"$(nproc)" \
    && make install)
  log "crun installed: $(/usr/local/bin/crun --version | head -1)"
  log "apply cluster/manifests/runtimeclass-crun.yaml and set runtimeClassName"
}

verify() {  # smoke checks (reference crio_setup.sh:69-70, README.md:49)
  log "verify:"
  run systemctl is-active "$RUNTIME" || true
  command -v crictl >/dev/null && run crictl --version || true
}

main() {
  case "$RUNTIME" in
    containerd) install_containerd ;;
    crio) install_crio ;;
    *) err "unknown --runtime=$RUNTIME"; exit 1 ;;
  esac
  install_crun_from_source
  install_crictl
  verify
}
main
