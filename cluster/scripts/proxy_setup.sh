#!/usr/bin/env bash
# proxy_setup.sh — egress/artifact-access layer for restricted networks.
#
# Generalizes the reference's L0 proxy stack (SURVEY §1 L0): Xray VLESS
# client -> SOCKS5 :1080 (reference xray_setup.sh:18,50-91), or a persistent
# ssh -N -D dynamic tunnel as a systemd unit (reference ssh-tunel.md:42-83),
# bridged to HTTP by privoxy on :8118 (reference privoxy_setup.sh:20-21).
# Every upper layer consumes one env var, HTTP_PROXY_URL=http://127.0.0.1:8118.
#
# Modes:
#   --mode=ssh   SSH dynamic tunnel (needs TUNNEL_* env or /etc/kgct/tunnel.env)
#   --mode=xray  Xray VLESS client -> SOCKS5 :1080 (needs XRAY_VLESS_URL or a
#                prepared XRAY_CONFIG json; reference xray_setup.sh:50-91)
#   --mode=none  write registry-mirror config only (default: air-gapped TPU
#                clusters usually mirror images instead of proxying)
#   --mode=privoxy-only  bridge an existing SOCKS5 at $SOCKS5_PORT to :8118
#
# Self-test at the end mirrors the reference's curl check
# (reference privoxy_setup.sh:32-38, README.md:28-31).
set -euo pipefail

MODE="none"
SOCKS5_PORT="${SOCKS5_PORT:-1111}"
HTTP_PORT="${HTTP_PORT:-8118}"
ENV_FILE="${ENV_FILE:-/etc/kgct/tunnel.env}"
REGISTRY_MIRROR="${REGISTRY_MIRROR:-}"
XRAY_VLESS_URL="${XRAY_VLESS_URL:-}"     # vless://uuid@host:port?...
XRAY_CONFIG="${XRAY_CONFIG:-}"           # or: path to a prepared config.json
XRAY_SOCKS_PORT="${XRAY_SOCKS_PORT:-1080}"
DRY_RUN="${DRY_RUN:-0}"

log() { echo -e "\e[32m[proxy]\e[0m $*"; }
err() { echo -e "\e[31m[proxy]\e[0m $*" >&2; }
run() { if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: $*"; else "$@"; fi }

RENDER_ONLY_URL=""
for arg in "$@"; do
  case "$arg" in
    --mode=*) MODE="${arg#*=}" ;;
    --render-xray-config=*) RENDER_ONLY_URL="${arg#*=}" ;;  # print json + exit
    *) err "unknown flag $arg"; exit 1 ;;
  esac
done

setup_ssh_tunnel() {
  # .env-driven persistent SOCKS5 tunnel as a systemd unit with
  # Restart=always (reference ssh-tunel.md:17-26,57-74)
  if [[ "$DRY_RUN" != "1" ]]; then
    # shellcheck disable=SC1090
    [[ -f "$ENV_FILE" ]] && source "$ENV_FILE"
    : "${TUNNEL_HOST:?set TUNNEL_HOST in $ENV_FILE}"
    : "${TUNNEL_USER:?set TUNNEL_USER in $ENV_FILE}"
    : "${TUNNEL_PORT:=22}"
  fi
  log "installing kgct-tunnel.service (SOCKS5 :$SOCKS5_PORT via ${TUNNEL_HOST:-\$TUNNEL_HOST})"
  [[ "$DRY_RUN" == "1" ]] && { echo "DRY: write kgct-tunnel unit"; return; }
  cat > /etc/systemd/system/kgct-tunnel.service <<EOF
[Unit]
Description=kgct persistent SOCKS5 ssh tunnel
After=network-online.target
Wants=network-online.target

[Service]
EnvironmentFile=$ENV_FILE
ExecStart=/usr/bin/ssh -N -D ${SOCKS5_PORT} \\
  -o ServerAliveInterval=30 -o ServerAliveCountMax=3 \\
  -o ExitOnForwardFailure=yes -o StrictHostKeyChecking=accept-new \\
  -p \${TUNNEL_PORT} \${TUNNEL_USER}@\${TUNNEL_HOST}
Restart=always
RestartSec=5

[Install]
WantedBy=multi-user.target
EOF
  systemctl daemon-reload
  systemctl enable --now kgct-tunnel.service
}

setup_xray() {
  # Xray VLESS client -> local SOCKS5 (reference xray_setup.sh:50-91 install +
  # config fetch; hardened service unit per xray_reset.sh:114-137: root,
  # Restart=always, NOFILE 65535)
  log "installing Xray VLESS client (SOCKS5 :$XRAY_SOCKS_PORT)"
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: install xray via official install-release.sh"
    echo "DRY: render /usr/local/etc/xray/config.json (socks :$XRAY_SOCKS_PORT -> vless outbound)"
    echo "DRY: systemd override Restart=always LimitNOFILE=65535"
    return 0
  fi
  if ! command -v xray >/dev/null; then
    bash -c "$(curl -L https://github.com/XTLS/Xray-install/raw/main/install-release.sh)" \
      @ install || { err "xray install failed"; exit 1; }
  fi
  mkdir -p /usr/local/etc/xray
  if [[ -n "$XRAY_CONFIG" ]]; then
    cp "$XRAY_CONFIG" /usr/local/etc/xray/config.json
  elif [[ -n "$XRAY_VLESS_URL" ]]; then
    render_xray_config "$XRAY_VLESS_URL" > /usr/local/etc/xray/config.json
  else
    err "set XRAY_VLESS_URL or XRAY_CONFIG for --mode=xray"; exit 1
  fi
  mkdir -p /etc/systemd/system/xray.service.d
  cat > /etc/systemd/system/xray.service.d/override.conf <<'EOF'
[Service]
User=root
Restart=always
RestartSec=3
LimitNOFILE=65535
EOF
  systemctl daemon-reload
  systemctl enable --now xray
  systemctl restart xray
  sleep 2
  # socks5h: resolve THROUGH the proxy — local DNS is poisoned on exactly the
  # networks this mode exists for
  curl -fsS --max-time 15 --proxy "socks5h://127.0.0.1:$XRAY_SOCKS_PORT" \
    https://ipinfo.io/ip >/dev/null \
    || { err "xray SOCKS5 self-test failed"; exit 1; }
  log "xray SOCKS5 up on :$XRAY_SOCKS_PORT"
}

render_xray_config() {
  # vless://<uuid>@<host>:<port>?security=tls&type=ws&sni=...&path=...#name
  # (the standard share-link shape) -> client config json. Unsupported
  # security/type values fail loudly rather than degrading to plaintext.
  local url="${1%%#*}"                      # strip #fragment
  local body="${url#vless://}"
  local uuid="${body%%@*}"
  local rest="${body#*@}"
  local hostport="${rest%%\?*}"
  local query=""
  [[ "$rest" == *\?* ]] && query="${rest#*\?}"
  local host="${hostport%%:*}"
  local port="${hostport##*:}"
  local security="none" net="tcp" sni="" wspath="/"
  urldecode() {  # %2F etc. — share-link exports percent-encode path/sni
    local s="${1//+/ }"
    printf '%b' "${s//%/\\x}"
  }
  local kv
  IFS='&' read -ra kv <<< "$query"
  for pair in "${kv[@]}"; do
    case "$pair" in
      security=*) security="$(urldecode "${pair#*=}")" ;;
      type=*) net="$(urldecode "${pair#*=}")" ;;
      sni=*) sni="$(urldecode "${pair#*=}")" ;;
      path=*) wspath="$(urldecode "${pair#*=}")" ;;
    esac
  done
  [[ "$port" =~ ^[0-9]+$ ]] || { err "bad port in VLESS url: $port"; exit 1; }
  case "$security" in none|tls) ;; *)
    err "unsupported VLESS security=$security (none|tls)"; exit 1 ;; esac
  case "$net" in tcp|ws) ;; *)
    err "unsupported VLESS type=$net (tcp|ws)"; exit 1 ;; esac
  local stream="\"network\": \"$net\", \"security\": \"$security\""
  [[ "$security" == "tls" ]] && \
    stream="$stream, \"tlsSettings\": {\"serverName\": \"${sni:-$host}\"}"
  [[ "$net" == "ws" ]] && \
    stream="$stream, \"wsSettings\": {\"path\": \"$wspath\"}"
  cat <<EOF
{
  "inbounds": [{
    "listen": "127.0.0.1", "port": $XRAY_SOCKS_PORT, "protocol": "socks",
    "settings": {"udp": true}
  }],
  "outbounds": [{
    "protocol": "vless",
    "settings": {"vnext": [{"address": "$host", "port": $port,
      "users": [{"id": "$uuid", "encryption": "none"}]}]},
    "streamSettings": {$stream}
  }]
}
EOF
}

setup_privoxy() {
  # HTTP :8118 -> SOCKS5 bridge (reference privoxy_setup.sh:13-21: config is
  # backed up, then forward-socks5 line swapped in)
  log "installing privoxy bridge :$HTTP_PORT -> socks5 127.0.0.1:$SOCKS5_PORT"
  [[ "$DRY_RUN" == "1" ]] && { echo "DRY: apt install privoxy + config"; return; }
  apt-get install -y privoxy
  local cfg=/etc/privoxy/config
  cp -n "$cfg" "$cfg.kgct.bak" || true
  sed -i -E 's@^\s*forward-socks5.*@@' "$cfg"
  echo "forward-socks5 / 127.0.0.1:$SOCKS5_PORT ." >> "$cfg"
  sed -i -E "s@^listen-address\s.*@listen-address 127.0.0.1:$HTTP_PORT@" "$cfg"
  systemctl restart privoxy
}

setup_registry_mirror() {
  # The TPU-era generalization: air-gapped clusters pull through a mirror
  # instead of a proxy (SURVEY §1 L0 "TPU translation").
  [[ -z "$REGISTRY_MIRROR" ]] && { log "no REGISTRY_MIRROR set; skipping"; return; }
  log "configuring containerd registry mirror -> $REGISTRY_MIRROR"
  [[ "$DRY_RUN" == "1" ]] && { echo "DRY: write hosts.toml"; return; }
  for reg in docker.io registry.k8s.io ghcr.io; do
    mkdir -p "/etc/containerd/certs.d/$reg"
    cat > "/etc/containerd/certs.d/$reg/hosts.toml" <<EOF
server = "https://$reg"

[host."$REGISTRY_MIRROR"]
  capabilities = ["pull", "resolve"]
EOF
  done
  systemctl restart containerd 2>/dev/null || true
}

self_test() {  # reference privoxy_setup.sh:32-38
  [[ "$MODE" == "none" || "$DRY_RUN" == "1" ]] && return 0
  log "self-test via http://127.0.0.1:$HTTP_PORT"
  if curl -fsS --max-time 20 --proxy "http://127.0.0.1:$HTTP_PORT" \
       https://ipinfo.io/ip >/dev/null; then
    log "proxy egress OK"
  else
    err "proxy self-test FAILED"; exit 1
  fi
}

main() {
  if [[ -n "$RENDER_ONLY_URL" ]]; then   # config-render debug/test entry
    render_xray_config "$RENDER_ONLY_URL"
    exit 0
  fi
  case "$MODE" in
    ssh) setup_ssh_tunnel; setup_privoxy ;;
    xray) SOCKS5_PORT="$XRAY_SOCKS_PORT"; setup_xray; setup_privoxy ;;
    privoxy-only) setup_privoxy ;;
    none) ;;
    *) err "unknown --mode=$MODE (ssh|xray|privoxy-only|none)"; exit 1 ;;
  esac
  setup_registry_mirror
  self_test
  log "done. export HTTP_PROXY_URL=http://127.0.0.1:$HTTP_PORT for the other scripts"
}
main
