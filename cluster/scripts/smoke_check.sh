#!/usr/bin/env bash
# smoke_check.sh — automated acceptance checks for a kgct TPU cluster.
#
# The reference's quality story was a ladder of MANUAL smoke checks with
# expected outputs pasted in runbooks (SURVEY §4's table: proxy curl
# README.md:28-31, runtime up README.md:49, port preconditions
# old_README.md:124-142, node Ready README.md:63-75, allocatable-GPU query
# old_README.md:569-574, in-pod device audit old_README.md:1014-1023, CUDA
# vectoradd acceptance old_README.md:716-734). This script IS that table,
# executable: each row is a check function printing PASS/FAIL/SKIP; exit
# code = number of failures.
#
# Usage:
#   bash smoke_check.sh                  # run everything applicable
#   bash smoke_check.sh proxy runtime    # run specific checks
#   DRY_RUN=1 bash smoke_check.sh        # print what would run
#   ACCEPTANCE_IMAGE=... bash smoke_check.sh acceptance
set -uo pipefail

DRY_RUN="${DRY_RUN:-0}"
PROXY_URL="${PROXY_URL:-http://127.0.0.1:8118}"
CRI_SOCKET="${CRI_SOCKET:-unix:///run/containerd/containerd.sock}"
# Image for the acceptance pod: anything with python3 + jax (the serving
# image works; any jax-on-tpu image does).
ACCEPTANCE_IMAGE="${ACCEPTANCE_IMAGE:-ghcr.io/kgct/tpu-serving:v0.3.0}"
ACCEPTANCE_TIMEOUT="${ACCEPTANCE_TIMEOUT:-300s}"

PASS=0; FAIL=0; SKIP=0
pass() { echo "PASS  $1"; PASS=$((PASS+1)); }
fail() { echo "FAIL  $1${2:+ — $2}"; FAIL=$((FAIL+1)); }
skip() { echo "SKIP  $1${2:+ — $2}"; SKIP=$((SKIP+1)); }

dry() { [[ "$DRY_RUN" == "1" ]]; }

# --- row 1: proxy egress (reference README.md:28-31) ------------------------
check_proxy() {
  dry && { echo "DRY: curl --proxy $PROXY_URL https://ipinfo.io/ip"; return; }
  if ! command -v curl >/dev/null; then skip proxy "no curl"; return; fi
  if curl -fs --max-time 10 --proxy "$PROXY_URL" https://ipinfo.io/ip >/dev/null; then
    pass "proxy egress via $PROXY_URL"
  else
    skip "proxy egress" "no proxy at $PROXY_URL (fine on open networks)"
  fi
}

# --- row 2: container runtime up (reference README.md:49) -------------------
check_runtime() {
  dry && { echo "DRY: systemctl is-active containerd; crictl version"; return; }
  if systemctl is-active --quiet containerd 2>/dev/null \
     || systemctl is-active --quiet crio 2>/dev/null; then
    pass "container runtime active"
  else
    fail "container runtime active" "neither containerd nor crio running"
  fi
  if command -v crictl >/dev/null; then
    if crictl --runtime-endpoint "$CRI_SOCKET" version >/dev/null 2>&1; then
      pass "CRI socket answers ($CRI_SOCKET)"
    else
      fail "CRI socket answers" "$CRI_SOCKET"
    fi
  else
    skip "CRI socket answers" "no crictl"
  fi
}

# --- row 3: port preconditions pre-init (reference old_README.md:124-142) ---
check_ports() {
  dry && { echo "DRY: ss -lptn sport = :6443"; return; }
  if ! command -v ss >/dev/null; then skip ports "no ss"; return; fi
  if kubectl get nodes >/dev/null 2>&1; then
    # cluster running: 6443 SHOULD be listening
    if ss -ltn 'sport = :6443' | grep -q 6443; then
      pass "apiserver listening on 6443"
    else
      fail "apiserver listening on 6443"
    fi
  else
    if ss -ltn 'sport = :6443' | grep -q 6443; then
      fail "port 6443 free pre-init" "something is listening"
    else
      pass "port 6443 free pre-init"
    fi
  fi
}

# --- row 4: node Ready (reference README.md:63-75) --------------------------
check_nodes() {
  dry && { echo "DRY: kubectl get nodes -> all Ready"; return; }
  command -v kubectl >/dev/null || { skip nodes "no kubectl"; return; }
  kubectl get nodes >/dev/null 2>&1 || { skip nodes "no cluster"; return; }
  local notready
  notready=$(kubectl get nodes --no-headers 2>/dev/null | awk '$2 != "Ready"' | wc -l)
  if [[ "$notready" == "0" ]]; then
    pass "all nodes Ready"
  else
    fail "all nodes Ready" "$notready node(s) not Ready"
  fi
}

# --- row 5: allocatable TPU (reference old_README.md:569-574) ---------------
check_allocatable() {
  dry && { echo "DRY: kubectl get nodes -o custom-columns=TPU:.status.allocatable.google\\.com/tpu"; return; }
  command -v kubectl >/dev/null || { skip allocatable "no kubectl"; return; }
  kubectl get nodes >/dev/null 2>&1 || { skip allocatable "no cluster"; return; }
  local total
  total=$(kubectl get nodes -o jsonpath='{range .items[*]}{.status.allocatable.google\.com/tpu}{"\n"}{end}' \
          2>/dev/null | awk '{s+=$1} END {print s+0}')
  if [[ "${total:-0}" -gt 0 ]]; then
    pass "allocatable google.com/tpu = $total"
  else
    fail "allocatable google.com/tpu" "0 — is the device plugin DaemonSet running?"
  fi
}

# --- row 6: device plugin registered (reference old_README.md:1206-1318) ----
check_device_plugin() {
  dry && { echo "DRY: kubectl -n kube-system logs ds/kgct-tpu-device-plugin | grep registered"; return; }
  command -v kubectl >/dev/null || { skip device-plugin "no kubectl"; return; }
  kubectl get ds -n kube-system kgct-tpu-device-plugin >/dev/null 2>&1 \
    || { skip device-plugin "DaemonSet not applied"; return; }
  if kubectl -n kube-system logs ds/kgct-tpu-device-plugin --tail=200 2>/dev/null \
       | grep -q "registered google.com/tpu"; then
    pass "device plugin registered with kubelet"
  else
    fail "device plugin registered" "no registration line in logs"
  fi
}

# --- row 7: end-to-end TPU acceptance pod (reference old_README.md:716-734,
#            the CUDA vectoradd analogue: tiny JAX matmul on 1 chip) --------
check_acceptance() {
  local manifest
  manifest=$(cat <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: kgct-tpu-acceptance
spec:
  restartPolicy: Never
  containers:
    - name: matmul
      image: $ACCEPTANCE_IMAGE
      command: ["python3", "-c"]
      args:
        - |
          import jax, jax.numpy as jnp
          assert jax.default_backend() == "tpu", jax.default_backend()
          x = jnp.ones((1024, 1024), jnp.bfloat16)
          y = (x @ x).block_until_ready()
          assert float(y[0, 0]) == 1024.0, y[0, 0]
          print("TPU MATMUL OK on", jax.devices())
      resources:
        limits:
          google.com/tpu: 1
EOF
)
  dry && { echo "DRY: kubectl apply TPU acceptance pod (google.com/tpu: 1) + wait $ACCEPTANCE_TIMEOUT"; return; }
  command -v kubectl >/dev/null || { skip acceptance "no kubectl"; return; }
  kubectl get nodes >/dev/null 2>&1 || { skip acceptance "no cluster"; return; }
  kubectl delete pod kgct-tpu-acceptance --ignore-not-found >/dev/null 2>&1
  echo "$manifest" | kubectl apply -f - >/dev/null || { fail acceptance "apply failed"; return; }
  if kubectl wait --for=jsonpath='{.status.phase}'=Succeeded \
       pod/kgct-tpu-acceptance --timeout="$ACCEPTANCE_TIMEOUT" >/dev/null 2>&1 \
     && kubectl logs kgct-tpu-acceptance | grep -q "TPU MATMUL OK"; then
    pass "TPU acceptance pod (matmul on google.com/tpu: 1)"
  else
    fail "TPU acceptance pod" "$(kubectl get pod kgct-tpu-acceptance \
      -o jsonpath='{.status.phase}' 2>/dev/null)"
  fi
  kubectl delete pod kgct-tpu-acceptance --ignore-not-found >/dev/null 2>&1
}

# --- row 8: serving E2E (reference old_README.md:1174-1176,1472-1476) -------
check_serving() {
  dry && { echo "DRY: curl kgct-router-service /health + /v1/models"; return; }
  command -v kubectl >/dev/null || { skip serving "no kubectl"; return; }
  kubectl get svc kgct-router-service >/dev/null 2>&1 \
    || { skip serving "router service not deployed"; return; }
  local out
  out=$(kubectl run kgct-curl-probe --rm -i --restart=Never --quiet \
        --image=curlimages/curl -- \
        -fs --max-time 10 http://kgct-router-service/health 2>/dev/null)
  if [[ "$out" == *'"status"'* ]]; then
    pass "router /health answers in-cluster"
  else
    fail "router /health answers" "$out"
  fi
}

ALL_CHECKS=(proxy runtime ports nodes allocatable device_plugin acceptance serving)

main() {
  local checks=("${@:-}")
  [[ -z "${checks[0]:-}" ]] && checks=("${ALL_CHECKS[@]}")
  for c in "${checks[@]}"; do
    c="${c//-/_}"
    if declare -F "check_$c" >/dev/null; then
      "check_$c"
    else
      echo "unknown check: $c (known: ${ALL_CHECKS[*]})"; exit 2
    fi
  done
  echo "----"
  echo "smoke: $PASS passed, $FAIL failed, $SKIP skipped"
  exit "$FAIL"
}

main "$@"
