#!/usr/bin/env bash
# tpu_node_setup.sh — reset-first Kubernetes bootstrap for TPU VM nodes.
#
# TPU-native equivalent of the reference's k8s_setup.sh (the "big one",
# reference k8s_setup.sh:1-432): every invocation FIRST tears down any prior
# kubernetes state, then converges the node to a clean control-plane or worker
# join — the reset-then-converge recovery property the reference's users
# relied on (reference k8s_setup.sh:375-424, SURVEY §7 hard part (e)).
#
# Differences from the reference, by design (TPU VMs, not bare GPU metal):
#   - containerd (stock on TPU VM images) instead of CRI-O; the CRI socket is
#     a flag, both run (reference pinned CRI-O, crio_setup.sh:19-41).
#   - no NVIDIA toolkit/CDI chain: TPU chips appear as /dev/vfio or /dev/accel
#     devices handled by the kgct device plugin DaemonSet (cluster/device-plugin),
#     replacing gpu-crio-setup.sh:87-126.
#   - ICI topology node labels are applied at join time so the scheduler can
#     pack TP groups onto one slice (replaces gpu=true labeling,
#     reference values-01-minimal-example2.yaml:19-20 / README.md:90).
#
# Usage:
#   sudo bash tpu_node_setup.sh --yes --role=control_plane
#   sudo bash tpu_node_setup.sh --yes --role=node \
#       --join="$(ssh cp 'kubeadm token create --print-join-command')"
#   DRY_RUN=1 bash tpu_node_setup.sh --role=control_plane   # print, don't do
set -euo pipefail

# ---------------------------------------------------------------------------
# config + flags (reference parse_args k8s_setup.sh:22-47)
# ---------------------------------------------------------------------------
KUBE_VERSION="${KUBE_VERSION:-v1.33}"
CRI_SOCKET="${CRI_SOCKET:-unix:///run/containerd/containerd.sock}"
POD_CIDR="${POD_CIDR:-10.244.0.0/16}"
SERVICE_CIDR="${SERVICE_CIDR:-10.96.0.0/12}"
HTTP_PROXY_URL="${HTTP_PROXY_URL:-}"     # optional egress proxy (proxy_setup.sh)
# CNI: pinned Calico (the reference's choice, v3.28 — reference README.md:78,
# node-Ready gate journaled old_README.md:365-399). APPLY_CNI=0 to skip.
APPLY_CNI="${APPLY_CNI:-1}"
CALICO_VERSION="${CALICO_VERSION:-v3.28.0}"
CNI_MANIFEST="${CNI_MANIFEST:-https://raw.githubusercontent.com/projectcalico/calico/$CALICO_VERSION/manifests/calico.yaml}"
ROLE=""
JOIN_CMD=""
ASSUME_YES=0
DRY_RUN="${DRY_RUN:-0}"

log()  { echo -e "\e[32m[tpu-setup]\e[0m $*"; }
warn() { echo -e "\e[33m[tpu-setup]\e[0m $*" >&2; }
err()  { echo -e "\e[31m[tpu-setup]\e[0m $*" >&2; }

run() {  # every state-changing command goes through run() => DRY_RUN-able
  if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: $*"; else "$@"; fi
}

usage() {
  grep '^#' "$0" | head -30; exit 1
}

for arg in "$@"; do
  case "$arg" in
    --yes) ASSUME_YES=1 ;;
    --role=*) ROLE="${arg#*=}" ;;
    --join=*) JOIN_CMD="${arg#*=}" ;;
    --kube-version=*) KUBE_VERSION="${arg#*=}" ;;
    --cri-socket=*) CRI_SOCKET="${arg#*=}" ;;
    --help|-h) usage ;;
    *) err "unknown flag: $arg"; usage ;;
  esac
done

require_root() {  # reference k8s_setup.sh:53-57
  if [[ "$DRY_RUN" != "1" && "$(id -u)" -ne 0 ]]; then
    err "must run as root (or DRY_RUN=1)"; exit 1
  fi
}

confirm() {  # destructive-op gate (reference k8s_setup.sh:59-63)
  [[ "$ASSUME_YES" == "1" || "$DRY_RUN" == "1" ]] && return 0
  read -r -p "$1 [y/N] " ans
  [[ "$ans" == "y" || "$ans" == "Y" ]]
}

# ---------------------------------------------------------------------------
# phase 1: teardown (reference k8s_setup.sh:375-392, :67-163)
# ---------------------------------------------------------------------------
teardown() {
  log "reset-first teardown"
  run systemctl stop kubelet 2>/dev/null || true
  run systemctl disable kubelet 2>/dev/null || true
  # kill any stray apiserver and free 6443 (reference :136-163)
  run pkill -9 -f kube-apiserver 2>/dev/null || true
  if command -v ss >/dev/null; then
    local pids
    pids=$(ss -lptn 'sport = :6443' 2>/dev/null \
           | grep -oP 'pid=\K[0-9]+' | sort -u || true)
    for p in $pids; do run kill -9 "$p" || true; done
  fi
  if confirm "remove /etc/kubernetes /var/lib/kubelet /var/lib/etcd ~/.kube?"; then
    run rm -rf /etc/kubernetes /var/lib/kubelet /var/lib/etcd \
        "${SUDO_USER:+/home/$SUDO_USER/.kube}" /root/.kube
  fi
  run kubeadm reset -f 2>/dev/null || true
}

# ---------------------------------------------------------------------------
# phase 2: host prereqs — swap + kernel networking
# (reference k8s_setup.sh:165-261; TPU VMs usually ship swapless, still gated)
# ---------------------------------------------------------------------------
disable_swap() {
  log "disabling swap (runtime + units + fstab)"
  run swapoff -a || true
  # mask systemd swap units (reference :218-231)
  for unit in $(systemctl list-unit-files --type swap --no-legend 2>/dev/null \
                | awk '{print $1}'); do
    run systemctl mask "$unit" || true
  done
  # comment swap lines out of fstab with a timestamped backup (reference :187-216)
  if [[ -f /etc/fstab ]] && grep -qE '^[^#].*\sswap\s' /etc/fstab; then
    local backup="/etc/fstab.kgct-$(date +%s).bak"
    run cp /etc/fstab "$backup"
    run sed -i -E 's@^([^#].*\sswap\s.*)@#\1@' /etc/fstab
    log "fstab swap entries commented (backup: $backup)"
  fi
}

setup_netfilter() {
  log "kernel networking prereqs (br_netfilter, forwarding)"
  run modprobe br_netfilter || true
  run modprobe overlay || true
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: write /etc/modules-load.d/kgct-k8s.conf + sysctl"
    return
  fi
  cat > /etc/modules-load.d/kgct-k8s.conf <<EOF
br_netfilter
overlay
EOF
  cat > /etc/sysctl.d/99-kgct-k8s.conf <<EOF
net.bridge.bridge-nf-call-iptables  = 1
net.bridge.bridge-nf-call-ip6tables = 1
net.ipv4.ip_forward                 = 1
EOF
  sysctl --system >/dev/null
}

# ---------------------------------------------------------------------------
# phase 3: container runtime wiring (reference crio_setup.sh + k8s_setup.sh:291-316)
# ---------------------------------------------------------------------------
setup_runtime() {
  log "container runtime: containerd (systemd cgroups, proxy drop-in)"
  if ! command -v containerd >/dev/null && [[ "$DRY_RUN" != "1" ]]; then
    err "containerd not installed; run runtime_setup.sh first"; exit 1
  fi
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: configure containerd (SystemdCgroup=true)"
    if [[ -n "$HTTP_PROXY_URL" ]]; then
      echo "DRY: containerd http-proxy.conf NO_PROXY=$(no_proxy_value)"
    fi
    return 0
  fi
  mkdir -p /etc/containerd
  if ! containerd config dump 2>/dev/null | grep -q "SystemdCgroup = true"; then
    containerd config default \
      | sed 's/SystemdCgroup = false/SystemdCgroup = true/' \
      > /etc/containerd/config.toml
  fi
  # proxy drop-in so IMAGE PULLS traverse the egress proxy with cluster CIDRs
  # excluded — the hard-won NO_PROXY fix (reference k8s_setup.sh:291-316,
  # journaled old_README.md:659-684)
  if [[ -n "$HTTP_PROXY_URL" ]]; then
    mkdir -p /etc/systemd/system/containerd.service.d
    cat > /etc/systemd/system/containerd.service.d/http-proxy.conf <<EOF
[Service]
Environment="HTTP_PROXY=$HTTP_PROXY_URL"
Environment="HTTPS_PROXY=$HTTP_PROXY_URL"
Environment="NO_PROXY=$(no_proxy_value)"
EOF
  fi
  systemctl daemon-reload
  systemctl enable --now containerd
  systemctl restart containerd
}

no_proxy_value() {  # single source of truth, visible to DRY_RUN golden tests
  echo "localhost,127.0.0.1,10.0.0.0/8,$POD_CIDR,$SERVICE_CIDR,.svc,.cluster.local"
}

# ---------------------------------------------------------------------------
# phase 4: kubeadm/kubelet/kubectl install, pinned + held
# (reference install_k8s_apt k8s_setup.sh:263-289)
# ---------------------------------------------------------------------------
install_k8s() {
  log "installing kubeadm/kubelet/kubectl $KUBE_VERSION (pinned, apt-held)"
  if [[ "$DRY_RUN" == "1" ]]; then echo "DRY: apt install kube* $KUBE_VERSION"; return; fi
  command -v kubeadm >/dev/null && { log "kubeadm present, skipping"; return; }
  local keyring=/etc/apt/keyrings/kubernetes-apt-keyring.gpg
  mkdir -p /etc/apt/keyrings
  local curl_cmd=(curl -fsSL)
  [[ -n "$HTTP_PROXY_URL" ]] && curl_cmd+=(--proxy "$HTTP_PROXY_URL")
  "${curl_cmd[@]}" "https://pkgs.k8s.io/core:/stable:/$KUBE_VERSION/deb/Release.key" \
    | gpg --dearmor -o "$keyring"
  echo "deb [signed-by=$keyring] https://pkgs.k8s.io/core:/stable:/$KUBE_VERSION/deb/ /" \
    > /etc/apt/sources.list.d/kubernetes.list
  apt-get update
  apt-get install -y kubelet kubeadm kubectl
  apt-mark hold kubelet kubeadm kubectl
  systemctl enable kubelet
}

# ---------------------------------------------------------------------------
# phase 5: TPU enablement — detect chips, stage topology labels
# (replaces the reference's NVIDIA chain gpu-crio-setup.sh:58-126; the device
#  plugin DaemonSet advertises google.com/tpu, cluster/device-plugin/)
# ---------------------------------------------------------------------------
detect_tpu() {
  log "detecting TPU devices"
  local chips=0 topo="none" accel_type="none"
  if compgen -G "/dev/accel*" >/dev/null; then
    chips=$(ls /dev/accel* | wc -l)
  elif compgen -G "/dev/vfio/*" >/dev/null; then
    chips=$(ls /dev/vfio/ | grep -vc vfio || true)
  fi
  # TPU VM metadata (best effort; absent off-GCE)
  if command -v curl >/dev/null; then
    accel_type=$(curl -fs -H "Metadata-Flavor: Google" \
      "http://metadata.google.internal/computeMetadata/v1/instance/attributes/accelerator-type" \
      2>/dev/null || echo none)
    topo=$(curl -fs -H "Metadata-Flavor: Google" \
      "http://metadata.google.internal/computeMetadata/v1/instance/attributes/tpu-topology" \
      2>/dev/null || echo none)
  fi
  TPU_CHIPS="$chips"; TPU_TOPOLOGY="$topo"; TPU_ACCEL_TYPE="$accel_type"
  log "TPU: chips=$TPU_CHIPS type=$TPU_ACCEL_TYPE topology=$TPU_TOPOLOGY"
}

label_node() {  # ICI-topology labels for slice-packing scheduling
  local node="$1"
  [[ "$TPU_CHIPS" == "0" ]] && { warn "no TPU chips; skipping labels"; return; }
  run kubectl label node "$node" --overwrite \
    "kgct.io/tpu=true" \
    "kgct.io/tpu-chips=$TPU_CHIPS" \
    "kgct.io/tpu-topology=$TPU_TOPOLOGY" \
    "kgct.io/accelerator-type=$TPU_ACCEL_TYPE"
}

# ---------------------------------------------------------------------------
# phase 6: init / join (reference init_control_plane k8s_setup.sh:336-361,
#                       join_node :363-372)
# ---------------------------------------------------------------------------
init_control_plane() {
  log "kubeadm init (control plane)"
  local logf="/var/log/kgct-kubeadm-init-$(date +%s).log"
  local extra=()
  [[ -n "${CONTROL_PLANE_ENDPOINT:-}" ]] && \
    extra+=(--control-plane-endpoint "$CONTROL_PLANE_ENDPOINT" --upload-certs)
  run kubeadm init \
    --cri-socket="$CRI_SOCKET" \
    --pod-network-cidr="$POD_CIDR" \
    "${extra[@]}" 2>&1 | tee "$logf"
  # success heuristic: the join hint must be in the log (reference :354-359)
  if [[ "$DRY_RUN" != "1" ]] && ! grep -q 'kubeadm join .* --token' "$logf"; then
    err "kubeadm init did not produce a join command — see $logf"; exit 1
  fi
  post_init_kubeconfig
  if [[ "$DRY_RUN" != "1" ]]; then
    detect_tpu
    label_node "$(hostname | tr '[:upper:]' '[:lower:]')" || true
  fi
  apply_cni
  fix_coredns
  log "control plane up. Next:"
  log "  kubectl apply -f cluster/device-plugin/manifest/daemonset.yaml"
  log "  bash cluster/scripts/smoke_check.sh   # automated acceptance checks"
}

fix_coredns() {  # C30-class cluster hardening (reference old_README.md:780-850:
                 # CoreDNS health port clash + GODEBUG): move the health probe
                 # off :8181 when the host already binds it. Gated, optional.
  [[ "${FIX_COREDNS:-0}" != "1" ]] && return 0
  log "patching CoreDNS health port 8181 -> 8182 (reference failure mode)"
  if [[ "$DRY_RUN" == "1" ]]; then
    echo "DRY: kubectl -n kube-system patch configmap coredns (health :8182)"
    echo "DRY: kubectl -n kube-system rollout restart deployment coredns"
    return 0
  fi
  kubectl -n kube-system get configmap coredns -o yaml \
    | sed 's/health {/health :8182 {/; s/^\(\s*\)health$/\1health :8182/' \
    | kubectl apply -f -
  kubectl -n kube-system rollout restart deployment coredns
}

apply_cni() {  # pinned CNI + node-Ready gate (reference README.md:78,
               # watch flow old_README.md:365-399; was a manual step there)
  if [[ "$APPLY_CNI" != "1" ]]; then
    log "APPLY_CNI=0: skipping CNI; apply one for $POD_CIDR before joining nodes"
    return
  fi
  log "applying CNI: $CNI_MANIFEST"
  run kubectl apply -f "$CNI_MANIFEST"
  [[ "$DRY_RUN" == "1" ]] && { echo "DRY: wait for node Ready"; return; }
  log "waiting for node Ready (CNI up)"
  if ! kubectl wait --for=condition=Ready node --all --timeout=300s; then
    warn "node not Ready after 300s — inspect CNI pods:"
    warn "  kubectl get pods -n kube-system -o wide"
    return 1
  fi
  log "node Ready"
}

post_init_kubeconfig() {  # reference k8s_setup.sh:320-334
  [[ "$DRY_RUN" == "1" ]] && { echo "DRY: install kubeconfig"; return; }
  local target_user="${SUDO_USER:-root}"
  local home_dir; home_dir=$(eval echo "~$target_user")
  mkdir -p "$home_dir/.kube"
  cp -f /etc/kubernetes/admin.conf "$home_dir/.kube/config"
  chown "$(id -u "$target_user")":"$(id -g "$target_user")" "$home_dir/.kube/config"
}

join_node() {
  [[ -z "$JOIN_CMD" ]] && { err "--role=node requires --join=..."; exit 1; }
  # auto-append the CRI socket (reference k8s_setup.sh:41-44)
  [[ "$JOIN_CMD" != *"--cri-socket"* ]] && JOIN_CMD="$JOIN_CMD --cri-socket=$CRI_SOCKET"
  log "joining cluster"
  run bash -c "$JOIN_CMD"
  detect_tpu
  log "joined. Label from the control plane:"
  log "  kubectl label node $(hostname) kgct.io/tpu=true kgct.io/tpu-chips=$TPU_CHIPS kgct.io/tpu-topology=$TPU_TOPOLOGY"
}

# ---------------------------------------------------------------------------
# main (reference main() k8s_setup.sh:375-424: teardown ALWAYS runs; the role
# only gates the final step)
# ---------------------------------------------------------------------------
main() {
  require_root
  teardown
  disable_swap
  setup_netfilter
  setup_runtime
  install_k8s
  case "$ROLE" in
    control_plane) init_control_plane ;;
    node) join_node ;;
    "") log "no --role given: node reset + prereqs done (re-runnable)" ;;
    *) err "unknown --role=$ROLE (control_plane|node)"; exit 1 ;;
  esac
}
main
