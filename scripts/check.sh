#!/usr/bin/env bash
# Pre-image quality gate: kgct-lint (empty-findings baseline, no allowlist)
# then the tier-1 test suite. docker/build.sh runs this before building, so
# an image can never ship lint-dirty or test-broken code; run it standalone
# before any push for the same signal.
#
# Usage: scripts/check.sh [--lint-only] [--changed GIT_REF]
#   --lint-only        skip the tier-1 pytest run (seconds instead of
#                      minutes; the lint gate alone still blocks every
#                      rule violation)
#   --changed GIT_REF  lint only .py files touched vs GIT_REF (kgct-lint
#                      --changed): the pre-commit fast path, same rules
#
# Artifacts: the SARIF findings document lands next to the tier-1 log
# (/tmp/_kgct_check.sarif) so CI can upload it for PR annotation.
#
# Exit codes: 0 clean; non-zero on the first failing stage (pipefail —
# a tee'd pytest failure cannot launder its exit status).
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "${REPO_ROOT}"
LINT_ONLY=0
CHANGED_REF=""

while [[ $# -gt 0 ]]; do
  case "$1" in
    --lint-only) LINT_ONLY=1; shift ;;
    --changed) CHANGED_REF="${2:?--changed needs a git ref}"; shift 2 ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

echo ">> kgct-lint (empty-baseline gate)"
rm -f /tmp/_kgct_check.sarif
LINT_ARGS=(kubernetes_gpu_cluster_tpu bench.py --sarif /tmp/_kgct_check.sarif)
if [[ -n "${CHANGED_REF}" ]]; then
  LINT_ARGS+=(--changed "${CHANGED_REF}")
fi
python -m kubernetes_gpu_cluster_tpu.analysis.cli "${LINT_ARGS[@]}"

if [[ "${LINT_ONLY}" == 1 ]]; then
  echo ">> check.sh: lint clean (tier-1 skipped via --lint-only)"
  exit 0
fi

echo ">> tier-1 tests"
rm -f /tmp/_kgct_check.log
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider \
  2>&1 | tee /tmp/_kgct_check.log
rc=${PIPESTATUS[0]}
echo ">> check.sh: lint clean, tier-1 rc=${rc}"
exit "${rc}"
