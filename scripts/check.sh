#!/usr/bin/env bash
# Pre-image quality gate: kgct-lint (empty-findings baseline, no allowlist)
# then the tier-1 test suite. docker/build.sh runs this before building, so
# an image can never ship lint-dirty or test-broken code; run it standalone
# before any push for the same signal.
#
# Usage: scripts/check.sh [--lint-only]
#   --lint-only    skip the tier-1 pytest run (seconds instead of minutes;
#                  the lint gate alone still blocks every rule violation)
#
# Exit codes: 0 clean; non-zero on the first failing stage (pipefail —
# a tee'd pytest failure cannot launder its exit status).
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "${REPO_ROOT}"
LINT_ONLY=0

while [[ $# -gt 0 ]]; do
  case "$1" in
    --lint-only) LINT_ONLY=1; shift ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
done

echo ">> kgct-lint (empty-baseline gate)"
python -m kubernetes_gpu_cluster_tpu.analysis.cli kubernetes_gpu_cluster_tpu bench.py

if [[ "${LINT_ONLY}" == 1 ]]; then
  echo ">> check.sh: lint clean (tier-1 skipped via --lint-only)"
  exit 0
fi

echo ">> tier-1 tests"
rm -f /tmp/_kgct_check.log
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider \
  2>&1 | tee /tmp/_kgct_check.log
rc=${PIPESTATUS[0]}
echo ">> check.sh: lint clean, tier-1 rc=${rc}"
exit "${rc}"
