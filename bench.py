"""Serving throughput benchmark — prints ONE JSON line for the driver.

Metric: steady-state decode tokens/sec/chip on TinyLlama-1.1B (BASELINE
config 1's model) under continuous batching on whatever backend is default
(the driver runs this on the real TPU chip).

vs_baseline: the reference publishes no numbers (BASELINE.md "published: {}");
the north star is ">= A100-class throughput per chip". We normalize against
A100_VLLM_TOKS_PER_S, a representative vLLM decode throughput for this model
class on one A100 at the same batch size.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

# Representative single-A100 vLLM decode throughput, ~1B-class model, batch 64.
A100_VLLM_TOKS_PER_S = 6000.0

BATCH = 64
PROMPT_LEN = 128
MAX_NEW_TOKENS = 512        # per sequence; bench stops earlier by wall budget
WARMUP_WINDOWS = 4
BENCH_WINDOWS = 24


def main() -> None:
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    model_name = "tinyllama-1.1b" if on_tpu else "debug-tiny"
    cfg = EngineConfig(
        model=get_model_config(model_name),
        cache=CacheConfig(page_size=16,
                          num_pages=BATCH * ((PROMPT_LEN + MAX_NEW_TOKENS) // 16 + 2) + 1),
        scheduler=SchedulerConfig(
            max_num_seqs=BATCH, max_prefill_tokens=2048,
            decode_buckets=(BATCH,), prefill_buckets=(2048,)))
    engine = LLMEngine(cfg, eos_token_id=None)

    rng = np.random.default_rng(0)
    vocab = cfg.model.vocab_size
    params = SamplingParams(temperature=0.0, max_tokens=MAX_NEW_TOKENS)
    for i in range(BATCH):
        prompt = rng.integers(1, vocab, PROMPT_LEN).tolist()
        engine.add_request(f"bench-{i}", prompt, params)

    # Prefill all sequences (one or more ragged prefill steps), then warm up
    # the windowed-decode program.
    t0 = time.perf_counter()
    while engine.scheduler.waiting:
        engine.step()
    prefill_s = time.perf_counter() - t0
    for _ in range(WARMUP_WINDOWS):
        engine.step()

    t0 = time.perf_counter()
    new_tokens = 0
    for _ in range(BENCH_WINDOWS):
        outs = engine.step()
        if not outs:
            break
        new_tokens += sum(len(o.new_token_ids or []) for o in outs)
    elapsed = time.perf_counter() - t0

    toks_per_s = new_tokens / elapsed
    result = {
        "metric": f"decode_tokens_per_sec_per_chip[{model_name},B={BATCH},ctx={PROMPT_LEN}]",
        "value": round(toks_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(toks_per_s / A100_VLLM_TOKS_PER_S, 3),
        "backend": backend,
        "prefill_tokens_per_sec": round(BATCH * PROMPT_LEN / prefill_s, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
