"""Serving benchmark — prints ONE JSON line for the driver.

Primary metric (BASELINE.json north-star config 2): steady-state decode
tokens/sec/chip on **Llama-3-8B int4 (W4A16)** under continuous batching,
measured on whatever backend is default (the driver runs this on the real
TPU chip). The 8B int8 config runs alongside as the quant-ladder A/B (the
r1-r5 line), a TinyLlama-1.1B bf16 config as the continuity line with
rounds 1-4, and every config's JSON carries:

- prefill throughput + TTFT p50/p95 over THREE fresh-batch trials (one trial
  collapses all samples onto the per-step boundaries; see VERDICT r4 weak #2)
- greedy AND sampled (temperature=1.0, top_k=50, top_p=0.95) decode rates —
  serving traffic is not greedy, so the sampled path is measured, not assumed
- a roofline block: modeled HBM bytes/token and FLOPs/token against the
  chip's peak HBM bandwidth and bf16 matmul throughput (``hbm_bw_util``,
  ``mfu``) so "is this fast?" has an arithmetic answer, not a vibe
- for the primary config, a sustained-load phase: Poisson arrivals at ~70%
  of measured decode capacity, reporting TTFT under load — the north star's
  "p50 TTFT under continuous batching" taken literally

Measurement discipline (r1 finding: never time XLA compilation): every
figure is collected AFTER a warmup phase that triggers every jit compile.
The bench chip is tunnel-attached (~110 ms host<->device round trip); decode
throughput hides it via speculative window chaining, TTFT/prefill include it
(``ttft_breakdown`` attributes the split).

vs_baseline: the reference publishes no numbers (BASELINE.md "published:
{}"); the bar is a SELF-CHOSEN representative single-A100 vLLM decode
throughput per model class, labeled as such in the output.
"""

from __future__ import annotations

import argparse
import gc
import json
import math
import os
import sys
import time

import jax
import numpy as np

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams
from kubernetes_gpu_cluster_tpu.utils import cdiv

# SELF-CHOSEN comparison bars, not measured or published numbers: vLLM-class
# single-A100 decode throughput per model class (batch ~64 / ~32 for 8B).
A100_VLLM_TOKS_PER_S = {
    "tinyllama-1.1b": 6000.0,   # ~1B class
    "debug-tiny": 6000.0,       # CPU smoke path, ~1B bar for continuity
    "llama-3-8b": 1500.0,       # 8B class (BASELINE.json config 2)
    "llama-3-70b": 200.0,       # 70B class, per-chip share of an 8xA100 node
    "mixtral-8x7b": 800.0,      # MoE 47B-total/13B-active class
}

# Chip peaks for the roofline (TPU v5e public specs); overridable when the
# driver runs on different hardware. MXU peak is the bf16 number even for
# int8 serving: W8A16 converts inside the dot, the MACs are bf16.
CHIP_HBM_GBPS = float(os.environ.get("KGCT_CHIP_HBM_GBPS", 819.0))
CHIP_TFLOPS_BF16 = float(os.environ.get("KGCT_CHIP_TFLOPS_BF16", 197.0))

PROMPT_LEN = int(os.environ.get("KGCT_BENCH_PROMPT", 128))
# None = the engine's backend-derived page size (128 on TPU, 16 on CPU), so
# the bench measures the SHIPPED default config.
PAGE = (int(os.environ["KGCT_BENCH_PAGE"])
        if os.environ.get("KGCT_BENCH_PAGE") else None)
# Substeps per XLA program. Re-tuned in r4 after the kernel optimizations
# shortened per-substep device time: at matched token budgets W=48 beat
# W=32 in every interleaved pair — the fixed ~110 ms per-window tunnel
# round trip amortizes worse once substeps got faster.
DECODE_WINDOW = int(os.environ.get("KGCT_BENCH_WINDOW", 48))
# Prefill token budget per step — measured operating point after the
# segment-aware k-window prefill kernel (r4); see PARITY.md "TTFT lever".
PREFILL_BUDGET = int(os.environ.get("KGCT_BENCH_PREFILL_BUDGET", 4096))
WARMUP_WINDOWS = 3
BENCH_WINDOWS = int(os.environ.get("KGCT_BENCH_WINDOWS", 12))
PREFILL_TRIALS = 3
SAMPLED_WINDOWS = int(os.environ.get("KGCT_BENCH_SAMPLED_WINDOWS", 6))
LOAD_REQUESTS = int(os.environ.get("KGCT_BENCH_LOAD_REQS", 160))
LOAD_MAX_NEW = 128
LOAD_UTILIZATION = float(os.environ.get("KGCT_BENCH_LOAD_UTIL", 0.7))
# Overload phase: offered load ABOVE capacity, TTFT-budget admission control
# on — measures that shedding keeps admitted requests' TTFT inside budget
# while shed clients retry per Retry-After (the PR-2 QoS contract).
OVERLOAD_UTILIZATION = float(os.environ.get("KGCT_BENCH_OVERLOAD_UTIL", 1.3))
OVERLOAD_REQUESTS = int(os.environ.get("KGCT_BENCH_OVERLOAD_REQS", 64))
OVERLOAD_TTFT_BUDGET_MS = float(
    os.environ.get("KGCT_BENCH_TTFT_BUDGET_MS", 1000.0))
# Stall-free mixed prefill/decode batching (engine/mixed_batch.py). Default
# ON for the bench: the sustained-load phase is the north-star TTFT
# measurement and mixing is the scheduler-level fix it exists to validate;
# KGCT_BENCH_MIXED=0 runs the legacy prefill-else-decode policy (A/B).
MIXED_BATCH = os.environ.get("KGCT_BENCH_MIXED", "1") != "0"
# Speculative decoding phase (engine/spec/): greedy decode over a
# repetitive-suffix workload (the n-gram proposer's home turf), a three-way
# A/B on identically-seeded engines — off / n-gram / draft-MODEL (with
# acceptance-adaptive k) — reporting acceptance ratio, accepted tokens per
# spec step, and the draft-over-ngram speedup headline; plus a spec×mixed
# arm measuring chat TTFT with speculation AND mixed batching on against
# mixed-only (spec must no longer forfeit the stall-free TTFT win).
# KGCT_BENCH_SPEC=0 skips the phase; KGCT_BENCH_SPEC_K sets the draft
# length; KGCT_BENCH_SPEC_DRAFT names the draft preset (default: the
# target preset itself — same arch and seed, the oracle-draft harness
# ceiling; real small-draft checkpoints are a TPU-round story);
# KGCT_BENCH_SPEC_MIXED=0 skips the composition arm.
SPEC_BENCH = os.environ.get("KGCT_BENCH_SPEC", "1") != "0"
SPEC_K = int(os.environ.get("KGCT_BENCH_SPEC_K", 4))
SPEC_BATCH = int(os.environ.get("KGCT_BENCH_SPEC_BATCH", 4))
SPEC_MAX_NEW = int(os.environ.get("KGCT_BENCH_SPEC_MAX_NEW", 96))
SPEC_DRAFT = os.environ.get("KGCT_BENCH_SPEC_DRAFT", "")
SPEC_MIXED_BENCH = os.environ.get("KGCT_BENCH_SPEC_MIXED", "1") != "0"
SPEC_CHAT_PROBES = int(os.environ.get("KGCT_BENCH_SPEC_CHAT_PROBES", 6))
# Prefix-reuse phase (engine/kv_cache.PrefixCache): a shared-system-prompt
# workload — cold requests with unique prompts vs warm requests sharing a
# page-aligned prefix — showing warm-prefix TTFT collapsing toward the
# cost of prefilling only the unique tail. KGCT_BENCH_PREFIX=0 skips.
PREFIX_BENCH = os.environ.get("KGCT_BENCH_PREFIX", "1") != "0"
PREFIX_REQS = int(os.environ.get("KGCT_BENCH_PREFIX_REQS", 6))
PREFIX_TAIL = int(os.environ.get("KGCT_BENCH_PREFIX_TAIL", 16))
# KV-swap phase (engine/kv_cache two-tier cache): a session workload
# oversubscribed ~KGCT_BENCH_SWAP_OVERSUB x the HBM page pool, A/B
# swap-preemption (host-DRAM tier) vs recompute-preemption on
# identically-seeded engines, reporting resumed-session TTFT (preempt ->
# next emitted token) and preemption counts. KGCT_BENCH_SWAP=0 skips.
SWAP_BENCH = os.environ.get("KGCT_BENCH_SWAP", "1") != "0"
SWAP_SESSIONS = int(os.environ.get("KGCT_BENCH_SWAP_SESSIONS", 8))
SWAP_OVERSUB = float(os.environ.get("KGCT_BENCH_SWAP_OVERSUB", 2.0))
SWAP_MAX_NEW = int(os.environ.get("KGCT_BENCH_SWAP_MAX_NEW", 48))
# Router phase (serving/router.py prefix-affinity): a shared-prefix SESSION
# workload replayed through the REAL router over >= 2 in-process engine
# replicas, A/B least-inflight vs prefix-affinity on identically-seeded
# engines. Least-inflight scatters a session's repeat requests across
# replicas (each replica must re-prefill the shared prefix before its own
# cache warms); affinity routes them to the ring owner whose cache is
# already hot — the phase reports warm-request TTFT and per-replica
# prefix-cache hit ratios for both arms. Always runs debug-tiny engines
# (the phase measures ROUTING locality, not model speed, and on TPU the
# primary config's pool must not be re-instantiated N more times).
# KGCT_BENCH_ROUTER=0 skips.
ROUTER_BENCH = os.environ.get("KGCT_BENCH_ROUTER", "1") != "0"
ROUTER_REPLICAS = int(os.environ.get("KGCT_BENCH_ROUTER_REPLICAS", 2))
# Sessions deliberately coprime with the replica count: least-inflight's
# round-robin tie-break then alternates each session across replicas
# (the scatter the affinity policy exists to fix); an equal multiple would
# park session s on replica s % N by accident and hide the effect.
ROUTER_SESSIONS = int(os.environ.get("KGCT_BENCH_ROUTER_SESSIONS",
                                     ROUTER_REPLICAS + 1))
ROUTER_ROUNDS = int(os.environ.get("KGCT_BENCH_ROUTER_ROUNDS", 3))
# Disaggregation phase (serving/handoff.py + router prefill pool): a MIXED
# long-prefill/long-decode workload A/B'd through the real serving stack —
# 1 prefill + 1 decode replica (role-split, KV-page handoff) vs 2 colocated
# replicas, all identically seeded. Mixed batching is OFF in both arms so
# the colocated arm exhibits the full prefill/decode interference
# disaggregation removes (the DistServe regime; mixed batching only BOUNDS
# it). Sustained decode TPOT p95 and TTFT p50 come from ONE router scrape
# per arm (the relabeled per-replica histograms). Always debug-tiny
# engines, like the router phase. KGCT_BENCH_DISAGG=0 skips.
DISAGG_BENCH = os.environ.get("KGCT_BENCH_DISAGG", "1") != "0"
DISAGG_DECODE_SESSIONS = int(os.environ.get("KGCT_BENCH_DISAGG_SESSIONS", 3))
DISAGG_DECODE_ROUNDS = int(os.environ.get("KGCT_BENCH_DISAGG_ROUNDS", 2))
DISAGG_PREFILLS = int(os.environ.get("KGCT_BENCH_DISAGG_PREFILLS", 6))
DISAGG_MAX_NEW = int(os.environ.get("KGCT_BENCH_DISAGG_MAX_NEW", 16))

# Drain phase (session survivability A/B): an oversubscribed streaming
# session workload over 2 replicas behind the router; one replica begins a
# SIGTERM drain mid-stream, once with live KV migration (drain time is
# transfer-bound: push each running sequence to the peer, the router
# splices the resumed streams) and once with migration disabled via the
# migrate_fail chaos site (the pre-migration wait-it-out path: drain time
# is bound by the longest remaining decode). Headline
# ``drain_migrate_over_wait_seconds`` = migrate-arm drain seconds /
# wait-arm drain seconds. Always debug-tiny engines. KGCT_BENCH_DRAIN=0
# skips.
DRAIN_BENCH = os.environ.get("KGCT_BENCH_DRAIN", "1") != "0"
DRAIN_SESSIONS = int(os.environ.get("KGCT_BENCH_DRAIN_SESSIONS", 6))
DRAIN_MAX_NEW = int(os.environ.get("KGCT_BENCH_DRAIN_MAX_NEW", 48))

# Fleet-cache phase (serving/fleet_cache.py — global prefix cache over the
# handoff substrate): shared-prefix sessions warmed on an OWNER replica and
# then forced onto a NON-OWNER (the router's affinity-overflow case: the
# owner is over-bound, the pick lands elsewhere and carries the
# x-kgct-prefix-source hint). A/B on identically-seeded replica pairs:
# fleet cache ON pulls the owner's cached prefix into the non-owner's
# cache (streamed import, roofline-gated); OFF recomputes the full prefix.
# Headline ``fleet_prefix_pull_over_recompute_ttft`` = pull-arm warm TTFT
# p50 / recompute-arm's (< 1 = pulling beats re-prefilling). Always
# debug-tiny engines, like every multi-replica phase.
# KGCT_BENCH_FLEET_CACHE=0 skips.
FLEET_BENCH = os.environ.get("KGCT_BENCH_FLEET_CACHE", "1") != "0"
FLEET_SESSIONS = int(os.environ.get("KGCT_BENCH_FLEET_SESSIONS", 3))
# Shared-prefix length: long enough that the recompute arm's full prefill
# clearly exceeds one localhost pull + tail chunk on CPU.
FLEET_SHARED = int(os.environ.get("KGCT_BENCH_FLEET_SHARED", 384))

# Multi-tenant QoS phase (engine/qos.py): a mixed chat+batch workload at
# SATURATION — batch-tier jobs hold every scheduler seat while short
# interactive requests arrive one at a time — A/B'd on identically-seeded
# engines with QoS tiers on vs off. Off, each chat request queues until a
# whole batch job finishes; on, priority make-room preemption (swap-backed)
# and fair-share promotion admit it immediately. Headline
# ``qos_chat_ttft_protected_ratio`` = chat p95 TTFT with QoS / without
# (< 1 = protected). The phase also runs the per-tier ADMISSION ledger
# under a deterministic tenant_flood chaos burst, reporting the per-tier
# shed split (the overload must attribute to the batch tier alone).
# KGCT_BENCH_QOS=0 skips.
QOS_BENCH = os.environ.get("KGCT_BENCH_QOS", "1") != "0"
QOS_BATCH_SEQS = int(os.environ.get("KGCT_BENCH_QOS_BATCH", 4))
QOS_CHAT_REQS = int(os.environ.get("KGCT_BENCH_QOS_CHAT_REQS", 6))
QOS_BATCH_MAX_NEW = int(os.environ.get("KGCT_BENCH_QOS_BATCH_MAX_NEW", 48))
QOS_CHAT_MAX_NEW = int(os.environ.get("KGCT_BENCH_QOS_CHAT_MAX_NEW", 8))

# The stdout contract bench.py guarantees (also the --help epilog, and what
# tests/test_bench_contract.py pins): everything before the last line is
# free-form noise; the LAST non-empty stdout line is the result.
OUTPUT_CONTRACT = """\
Output contract (the driver's official record depends on it):

  The LAST non-empty line of stdout is the benchmark result — exactly one
  single-line JSON object (json.dumps, no embedded newlines), written and
  flushed after everything else. All logging goes to stderr; any earlier
  stdout noise is flushed before the result so interleaving cannot split
  the line. Consumers must parse ONLY that last line (parse_result_line()
  implements this), never scan stdout for something JSON-shaped.

  The result line is BOUNDED to RESULT_LINE_MAX bytes: capture harnesses
  keep only a stdout TAIL (the r5 record kept 2000 chars and decapitated
  an oversized result line into "parsed": null). When the full result
  would exceed the bound, the bulky per-config detail ("configs") moves to
  stderr as a FULL_RESULT line and the stdout result keeps every headline
  field plus "configs_on_stderr": true.
"""

# The driver's transcript tail window is 2000 chars (BENCH_r05.json);
# bound the result line well under it so a tail capture can never cut the
# line's head off again. tests/test_bench_contract.py pins this.
RESULT_LINE_MAX = 1600


def _mk_engine(model_name: str, quant, batch: int, max_new: int,
               window: int, budget: int, page_slack: int = 3):
    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    # Ceil-divide: a floor here under-provisions the pool whenever the page
    # size doesn't divide the sequence budget (fatal with page_slack=0).
    pages_per_seq = cdiv(PROMPT_LEN + max_new, page) + page_slack
    cfg = EngineConfig(
        model=get_model_config(model_name).replace(quantization=quant),
        cache=CacheConfig(page_size=page, num_pages=batch * pages_per_seq + 1),
        scheduler=SchedulerConfig(
            max_num_seqs=batch, max_prefill_tokens=budget,
            decode_buckets=(batch,), prefill_buckets=(budget,),
            decode_window=window, mixed_batch_enabled=MIXED_BATCH))
    return LLMEngine(cfg, eos_token_id=None)


def _add_batch(engine, rng, vocab, tag, batch, max_new, **samp):
    samp.setdefault("temperature", 0.0)
    params = SamplingParams(max_tokens=max_new, **samp)
    t = time.perf_counter()
    for i in range(batch):
        prompt = rng.integers(1, vocab, PROMPT_LEN).tolist()
        engine.add_request(f"{tag}-{i}", prompt, params)
    return t


def _drain(engine, tag, batch):
    for i in range(batch):
        engine.abort_request(f"{tag}-{i}")
    while engine.has_unfinished_requests():
        engine.step()


def _measure_host_rt_s() -> float:
    """Median host<->device round trip for a tiny dispatched op — ~110 ms on
    the tunnel-attached bench chip; dominates TTFT, reported separately."""
    x = jax.numpy.zeros((1,), jax.numpy.float32)
    f = jax.jit(lambda a: a + 1)
    f(x).block_until_ready()  # compile outside the timing
    ts = []
    for _ in range(5):
        t = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t)
    return sorted(ts)[len(ts) // 2]


def _median(xs, default=float("nan")):
    # ADVICE r4: never IndexError into an empty list and mask the real
    # misconfiguration (e.g. all requests finished during warmup).
    return sorted(xs)[len(xs) // 2] if xs else default


def _percentile(xs, q, default=float("nan")):
    if not xs:
        return default
    xs = sorted(xs)
    return xs[min(int(len(xs) * q), len(xs) - 1)]


# --------------------------------------------------------------------------
# Roofline model
# --------------------------------------------------------------------------

def _weight_stream_bytes(mcfg, quant) -> int:
    """Modeled HBM bytes to stream every matmul weight once (one decode
    step), at the quant ladder's REAL storage layout (ops/quant.py):
    bf16/f32 at dtype bytes per weight; int8 at 1 B/w plus one f32 scale
    per output channel; int4 at 0.5 B/w (two nibbles packed per byte) plus
    one f32 scale per (input group, output channel) — the scale overhead is
    what keeps int4 at ~0.53x int8, not an idealized 0.5x. MoE streams ALL
    expert weights (at serving batch sizes every expert is hit)."""
    h, inter = mcfg.hidden_size, mcfg.intermediate_size
    nh, nkv, hd = mcfg.num_heads, mcfg.num_kv_heads, mcfg.head_dim
    L, V = mcfg.num_layers, mcfg.vocab_size
    dtype_bytes = 2 if mcfg.dtype == "bfloat16" else 4
    n_exp = max(mcfg.num_experts, 1)
    gs = mcfg.quant_group_size
    # (in_dim, out_dim, count) per streamed matmul class — matches
    # ops/quant.QUANT_LAYER_KEYS plus lm_head.
    mats = [(h, nh * hd, L), (h, nkv * hd, 2 * L), (nh * hd, h, L),
            (h, inter, 2 * L * n_exp), (inter, h, L * n_exp)]
    if not mcfg.tie_word_embeddings:
        mats.append((h, V, 1))
    total = 0
    for din, dout, count in mats:
        if quant == "int4":
            per = din * dout // 2 + 4 * (din // gs) * dout
        elif quant == "int8":
            per = din * dout + 4 * dout
        else:
            per = din * dout * dtype_bytes
        total += per * count
    return total


def _roofline(mcfg, quant, batch: int, ctx: int) -> dict:
    """Modeled per-step HBM traffic and per-token matmul FLOPs for decode at
    context length ``ctx``. Weight-streaming accounting matches
    ops/quant.QUANT_LAYER_KEYS storage exactly (packed bytes + scales; see
    _weight_stream_bytes); embeddings/norms stream at the serving dtype.
    MoE streams ALL expert weights per step (at serving batch sizes every
    expert is hit) but only num_experts_per_tok experts contribute
    per-token FLOPs."""
    h, inter = mcfg.hidden_size, mcfg.intermediate_size
    nh, nkv, hd = mcfg.num_heads, mcfg.num_kv_heads, mcfg.head_dim
    L, V = mcfg.num_layers, mcfg.vocab_size

    attn_p = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
    mlp_unit = 3 * h * inter
    active_exp = mcfg.num_experts_per_tok if mcfg.is_moe else 1
    layer_active = attn_p + active_exp * mlp_unit       # flops: routed only

    # Per decode step: every matmul weight streams once (batch amortizes);
    # each sequence reads its KV history and writes one slot.
    kv_token_bytes = 2 * L * nkv * hd * 2               # bf16 KV
    weight_stream = _weight_stream_bytes(mcfg, quant)
    step_bytes = weight_stream + batch * kv_token_bytes * ctx
    # Per-token matmul FLOPs (2 per MAC) + attention score/value FLOPs.
    flops_per_token = 2 * (L * layer_active + V * h) + 4 * L * nh * hd * ctx
    return {
        "weight_stream_bytes": int(weight_stream),
        "kv_bytes_per_step": int(batch * kv_token_bytes * ctx),
        "step_bytes": int(step_bytes),
        "flops_per_token": int(flops_per_token),
    }


def _roofline_prefill(mcfg, quant, T: int) -> dict:
    """Modeled ragged-prefill step of ``T`` flattened prompt tokens — the
    arithmetic target TTFT optimization regresses against (ROADMAP item #5:
    the roofline used to model decode only while prefill was the weak
    phase).

    FLOPs: every matmul runs over all T tokens (2 FLOPs/MAC, routed experts
    only for MoE) plus causal attention score+value FLOPs (~T^2/2 valid
    pairs). Logits project only the B sampled rows, not T — excluded from
    FLOPs, like the decode model excludes sampling (the head WEIGHT still
    counts in the byte stream: it is read every sampling step). Bytes: the
    weight stream (every matmul weight once per step — amortized over T,
    which is why prefill is compute-bound where decode is
    weight-streaming-bound) plus the step's KV writes; activations are
    omitted (VMEM-resident at these shapes).
    ``flops_per_byte`` makes the regime explicit: compared against the
    chip's peak FLOPs/peak bandwidth ratio (~240 on v5e), prefill at
    budget-sized T sits far above it — any TTFT prefill-phase time beyond
    ``compute_bound_ms`` is overhead (padding, layout, host), not physics.
    """
    h, inter = mcfg.hidden_size, mcfg.intermediate_size
    nh, nkv, hd = mcfg.num_heads, mcfg.num_kv_heads, mcfg.head_dim
    L = mcfg.num_layers

    attn_p = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
    mlp_unit = 3 * h * inter
    active_exp = mcfg.num_experts_per_tok if mcfg.is_moe else 1
    layer_active = attn_p + active_exp * mlp_unit

    matmul_flops = 2 * T * L * layer_active
    attn_flops = 4 * L * nh * hd * (T * T) // 2     # causal: ~half the pairs
    flops_step = matmul_flops + attn_flops
    kv_token_bytes = 2 * L * nkv * hd * 2           # bf16 KV
    bytes_step = _weight_stream_bytes(mcfg, quant) + T * kv_token_bytes
    return {
        "tokens_modeled": int(T),
        "flops_per_step": int(flops_step),
        "flops_per_token": int(flops_step // max(T, 1)),
        "bytes_per_step": int(bytes_step),
        "flops_per_byte": round(flops_step / bytes_step, 1),
        "compute_bound_ms": round(
            flops_step / (CHIP_TFLOPS_BF16 * 1e12) * 1e3, 3),
        "hbm_bound_ms": round(bytes_step / (CHIP_HBM_GBPS * 1e9) * 1e3, 3),
    }


def _utilization(model_acct: dict, toks_per_s: float, batch: int) -> dict:
    steps_per_s = toks_per_s / batch
    hbm_gbps = steps_per_s * model_acct["step_bytes"] / 1e9
    mfu = toks_per_s * model_acct["flops_per_token"] / (CHIP_TFLOPS_BF16 * 1e12)
    return {
        "hbm_gbps": round(hbm_gbps, 1),
        "hbm_bw_util": round(hbm_gbps / CHIP_HBM_GBPS, 3),
        "mfu": round(mfu, 4),
    }


# --------------------------------------------------------------------------
# Measurement phases
# --------------------------------------------------------------------------

def _measure_prefill_ttft(engine, rng, vocab, batch, max_new, host_rt_s):
    """PREFILL_TRIALS fresh-batch prefill trials; TTFT samples pooled across
    trials so the percentiles stop being a 2-step boundary artifact. The
    LAST trial's batch is left running for the decode phase."""
    trial_rates, ttfts = [], []
    breakdown = None
    for t in range(PREFILL_TRIALS):
        tag = f"bench{t}"
        t_submit = _add_batch(engine, rng, vocab, tag, batch, max_new)
        first_token_at = {}
        steps = 0
        t0 = time.perf_counter()
        while engine.scheduler.waiting:
            outs = engine.step()
            steps += 1
            now = time.perf_counter()
            for o in outs:
                if o.new_token_ids and o.request_id not in first_token_at:
                    first_token_at[o.request_id] = now
        wall = time.perf_counter() - t0
        trial_rates.append(batch * PROMPT_LEN / wall)
        ttfts.extend(t - t_submit for t in first_token_at.values())
        breakdown = {
            "host_rt_ms": round(host_rt_s * 1e3, 1),
            "prefill_steps": steps,
            "prefill_wall_ms": round(wall * 1e3, 1),
            "est_prefill_compute_ms": round(
                max(wall - steps * host_rt_s, 0.0) * 1e3, 1),
        }
        if t < PREFILL_TRIALS - 1:
            _drain(engine, tag, batch)
    return {
        "prefill_tokens_per_sec": round(_median(trial_rates), 1),
        "prefill_trials": PREFILL_TRIALS,
        "ttft_p50_ms": round(_percentile(ttfts, 0.50) * 1e3, 1),
        "ttft_p95_ms": round(_percentile(ttfts, 0.95) * 1e3, 1),
        "ttft_breakdown": breakdown,
    }, f"bench{PREFILL_TRIALS - 1}"


def _measure_decode(engine, n_windows, phases=3):
    """Steady-state decode: one priming step so the speculative window chain
    is in flight, then ``phases`` consecutive phases whose MEDIAN rate is
    reported (the tunnel chip drifts ±15% across minutes; a median over
    temporally-close phases keeps one bad window from defining the number)."""
    outs = engine.step()
    phase_rates = []
    per_phase = max(1, n_windows // phases)
    for _ in range(phases):
        new_tokens = 0
        t0 = time.perf_counter()
        for _ in range(per_phase):
            outs = engine.step()
            if not outs:
                break
            new_tokens += sum(len(o.new_token_ids or []) for o in outs)
        elapsed = time.perf_counter() - t0
        if new_tokens:
            phase_rates.append(new_tokens / elapsed)
        if not outs:
            break
    return _median(phase_rates)


def _measure_sampled_decode(engine, rng, vocab, batch, max_new):
    """Fresh batch at temperature=1.0/top_k=50/top_p=0.95 — compiles and
    measures the SAMPLED decode program (real serving traffic is not
    greedy; r4's headline silently assumed it was)."""
    tag = "sampled"
    _add_batch(engine, rng, vocab, tag, batch, max_new,
               temperature=1.0, top_k=50, top_p=0.95)
    while engine.scheduler.waiting:
        engine.step()
    engine.step()   # first sampled window: compile + prime
    rate = _measure_decode(engine, SAMPLED_WINDOWS, phases=2)
    _drain(engine, tag, batch)
    return rate


def _measure_sustained(engine, rng, vocab, batch, rate_rps):
    """Poisson arrivals at ``rate_rps`` until LOAD_REQUESTS complete their
    first token. TTFT is measured from the scheduled ARRIVAL time (includes
    host/queueing delay — admission fairness under steady load), throughput
    over the whole phase."""
    n = LOAD_REQUESTS
    params = SamplingParams(temperature=0.0, max_tokens=LOAD_MAX_NEW)
    gaps = rng.exponential(1.0 / rate_rps, n)
    arrivals = np.cumsum(gaps)
    first_at, submitted = {}, 0
    new_tokens = 0
    start = time.perf_counter()
    while len(first_at) < n:
        now = time.perf_counter() - start
        while submitted < n and arrivals[submitted] <= now:
            prompt = rng.integers(1, vocab, PROMPT_LEN).tolist()
            engine.add_request(f"load-{submitted}", prompt, params)
            submitted += 1
        if engine.has_unfinished_requests():
            outs = engine.step()
            t_now = time.perf_counter() - start
            for o in outs:
                new_tokens += len(o.new_token_ids or [])
                if o.new_token_ids and o.request_id not in first_at:
                    first_at[o.request_id] = t_now
        elif submitted < n:
            time.sleep(min(arrivals[submitted] - now, 0.05))
    wall = time.perf_counter() - start
    for i in range(n):
        engine.abort_request(f"load-{i}")
    while engine.has_unfinished_requests():
        engine.step()
    ttfts = [first_at[f"load-{i}"] - arrivals[i] for i in range(n)
             if f"load-{i}" in first_at]
    return {
        "offered_rate_rps": round(rate_rps, 2),
        "n_requests": n,
        "ttft_p50_ms": round(_percentile(ttfts, 0.50) * 1e3, 1),
        "ttft_p95_ms": round(_percentile(ttfts, 0.95) * 1e3, 1),
        "throughput_tokens_per_sec": round(new_tokens / wall, 1),
    }


def _measure_overload(engine, rng, vocab, rate_rps, budget_ms):
    """Poisson arrivals ABOVE decode capacity with TTFT-budget admission
    control (resilience.AdmissionController — the same control loop the API
    server runs). A shed client honors Retry-After: it re-attempts after the
    advised backoff, up to ``max_retries`` times, then counts as dropped.
    Reports the shed/delivered split and whether ADMITTED requests kept
    their TTFT — the acceptance bar is that overload degrades the shed
    count, not the admitted requests' latency."""
    from kubernetes_gpu_cluster_tpu.resilience import AdmissionController

    n = OVERLOAD_REQUESTS
    max_retries = 2
    adm = AdmissionController(engine, default_budget_ms=budget_ms)
    # SLO layer reads for THIS phase: grade attainment against the same
    # budget admission control sheds on, over a window that starts here
    # (the sustained phase's samples would dilute the overload readout).
    engine.obs.slo.ttft_budget_ms = budget_ms
    engine.obs.slo.clear()
    params = SamplingParams(temperature=0.0, max_tokens=LOAD_MAX_NEW)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n))
    attempt_at = list(arrivals)          # next admission attempt per request
    retries = [0] * n
    pending = set(range(n))              # not yet admitted or dropped
    submit_at: dict = {}                 # i -> admission time
    first_at: dict = {}                  # i -> first-token time
    dropped: set = set()
    start = time.perf_counter()
    while len(first_at) + len(dropped) < n:
        now = time.perf_counter() - start
        for i in sorted(pending):
            if attempt_at[i] > now:
                continue
            retry_after = adm.check(None)
            if retry_after is None:
                prompt = rng.integers(1, vocab, PROMPT_LEN).tolist()
                engine.add_request(f"over-{i}", prompt, params)
                submit_at[i] = now
                pending.discard(i)
            elif retries[i] >= max_retries:
                dropped.add(i)
                pending.discard(i)
            else:
                retries[i] += 1
                attempt_at[i] = now + retry_after
        if engine.has_unfinished_requests():
            outs = engine.step()
            t_now = time.perf_counter() - start
            for o in outs:
                if (o.new_token_ids and o.request_id.startswith("over-")
                        and o.request_id not in first_at):
                    first_at[o.request_id] = t_now
        elif pending:
            nxt = min(attempt_at[i] for i in pending)
            time.sleep(min(max(nxt - now, 0.0), 0.05))
    # Re-key first-token times by request index for the TTFT join.
    first_by_i = {int(rid.split("-")[1]): t for rid, t in first_at.items()}
    for i in range(n):
        engine.abort_request(f"over-{i}")
    while engine.has_unfinished_requests():
        engine.step()
    # TTFT measured from the ADMITTED attempt (the request whose budget the
    # controller accepted), which is the QoS the 429 contract protects.
    ttfts = [first_by_i[i] - submit_at[i] for i in first_by_i]
    violations = sum(1 for t in ttfts if t * 1e3 > budget_ms)
    return {
        "offered_rate_rps": round(rate_rps, 2),
        "ttft_budget_ms": budget_ms,
        "n_requests": n,
        "delivered": len(first_by_i),
        "dropped_after_retries": len(dropped),
        "shed_attempts": adm.shed_total,
        "retried_clients": sum(1 for r in retries if r > 0),
        # None, not NaN, when everything was shed: json.dumps emits a bare
        # NaN token strict parsers reject — the exact guaranteed-last-line
        # regression the PR-1 emit contract exists to prevent.
        "ttft_p50_ms": (round(_percentile(ttfts, 0.50) * 1e3, 1)
                        if ttfts else None),
        "ttft_p95_ms": (round(_percentile(ttfts, 0.95) * 1e3, 1)
                        if ttfts else None),
        "ttft_budget_violations": violations,
        # The rolling SLO gauges the autoscaler (ROADMAP 4(b)) will consume,
        # read engine-side at phase end: attainment over the phase's
        # admitted requests and budget-meeting goodput. BENCH_r06 captures
        # attainment alongside raw TTFT.
        "slo_ttft_attainment_ratio": round(engine.obs.slo.attainment(), 3),
        "slo_goodput_tokens_per_sec": round(
            engine.obs.slo.goodput_tokens_per_sec(), 1),
    }


def _measure_spec(model_name: str, quant, rng) -> dict:
    """Speculative-decoding phase: greedy decode over a repetitive-suffix
    workload (prompts built from a short repeated pattern, so prompt-lookup
    drafts hit), a three-way A/B on engines with IDENTICAL weights (same
    config seed): off ("base"), n-gram ("spec"), and draft-MODEL with
    acceptance-adaptive k ("draft"). Reports per arm the acceptance ratio,
    accepted draft tokens per spec step (the >1.0 bar that makes a verify
    step beat a plain decode step in tokens), and decode tokens/sec; the
    draft arm adds the adaptive controller's live k and movement counts.

    Draft-model caveat (CPU): the default draft is the TARGET preset at
    the SAME seed — an oracle draft (acceptance ~1.0) that validates the
    two-model machinery and the adaptive ceiling, but whose per-token
    draft cost equals the target's, so `spec_draft_over_ngram_speedup`
    measures harness overhead, not the production win. The production
    ratio needs a genuinely small draft (KGCT_BENCH_SPEC_DRAFT, e.g.
    tinyllama-1.1b drafting for llama-3-8b) and real checkpoints — the
    BENCH_r06 TPU round (ROADMAP item 1(b)). Runs after the main config's
    engine is freed — on-chip, extra model instantiations must not
    overlap the big serving pool."""
    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    pattern = rng.integers(1, 200, 12).tolist()
    reps = cdiv(PROMPT_LEN, len(pattern))
    prompts = [(pattern * reps)[:PROMPT_LEN] for _ in range(SPEC_BATCH)]
    params = SamplingParams(max_tokens=SPEC_MAX_NEW, temperature=0.0)
    draft_name = SPEC_DRAFT or model_name
    out = {"k": SPEC_K, "batch": SPEC_BATCH, "max_new": SPEC_MAX_NEW,
           "draft_model": draft_name}

    arms = (("base", False, None), ("spec", True, None),
            ("draft", True, draft_name))
    for label, spec, draft in arms:
        pages_per_seq = cdiv(PROMPT_LEN + SPEC_MAX_NEW + SPEC_K, page) + 2
        cfg = EngineConfig(
            model=get_model_config(model_name).replace(quantization=quant),
            cache=CacheConfig(page_size=page,
                              num_pages=SPEC_BATCH * pages_per_seq + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=SPEC_BATCH, max_prefill_tokens=PREFILL_BUDGET,
                decode_buckets=(SPEC_BATCH,), prefill_buckets=(PREFILL_BUDGET,),
                decode_window=DECODE_WINDOW, mixed_batch_enabled=False,
                spec_decode_enabled=spec, num_speculative_tokens=SPEC_K,
                spec_draft_model=draft, spec_adaptive_k=draft is not None))
        engine = LLMEngine(cfg, eos_token_id=None)
        # Warmup pass compiles every program this workload touches (the
        # measurement discipline: never time XLA compilation).
        for i, p in enumerate(prompts):
            engine.add_request(f"warm-{i}", list(p), params)
        while engine.has_unfinished_requests():
            engine.step()
        for i, p in enumerate(prompts):
            engine.add_request(f"m-{i}", list(p), params)
        while engine.scheduler.waiting:
            engine.step()
        steps0 = engine.stats.steps
        drafted0 = engine.obs.spec_drafted_tokens
        accepted0 = engine.obs.spec_accepted_tokens
        spec_steps0 = engine.obs.step_kind_counts["spec"]
        new_tokens = 0
        t0 = time.perf_counter()
        while engine.has_unfinished_requests():
            new_tokens += sum(len(o.new_token_ids or [])
                              for o in engine.step())
        wall = time.perf_counter() - t0
        out[label] = {
            "decode_tokens_per_sec": round(new_tokens / wall, 1),
            "decode_steps": engine.stats.steps - steps0,
        }
        if spec:
            drafted = engine.obs.spec_drafted_tokens - drafted0
            accepted = engine.obs.spec_accepted_tokens - accepted0
            n_spec = engine.obs.step_kind_counts["spec"] - spec_steps0
            out[label].update({
                "spec_steps": n_spec,
                "drafted_tokens": drafted,
                "accepted_tokens": accepted,
                "acceptance_ratio": (round(accepted / drafted, 3)
                                     if drafted else None),
                "accepted_tokens_per_spec_step": (round(accepted / n_spec, 2)
                                                  if n_spec else None),
            })
        ctrl = engine.scheduler.spec_controller
        if ctrl is not None:
            out[label]["adaptive_k"] = {
                "current_k": ctrl.current_k, "ladder": list(ctrl.ladder),
                "steps_down": ctrl.num_steps_down,
                "steps_up": ctrl.num_steps_up,
            }
        del engine
        gc.collect()
    base, spec, draft = out["base"], out["spec"], out["draft"]
    out["speedup"] = (round(spec["decode_tokens_per_sec"]
                            / base["decode_tokens_per_sec"], 3)
                      if base["decode_tokens_per_sec"] else None)
    out["spec_draft_over_ngram_speedup"] = (
        round(draft["decode_tokens_per_sec"]
              / spec["decode_tokens_per_sec"], 3)
        if spec["decode_tokens_per_sec"] else None)
    if SPEC_MIXED_BENCH:
        out["spec_mixed"] = _measure_spec_mixed(model_name, quant, rng)
    return out


def _measure_spec_mixed(model_name: str, quant, rng) -> dict:
    """Spec×mixed composition arm: chat TTFT with speculation AND mixed
    batching on, against mixed-only. Before the composition landed,
    enabling spec forfeited the stall-free TTFT win (spec rows and a
    prefill chunk could not share a device step); now the mixed step
    carries every running row's verify slice plus the budgeted chunk, so
    chat TTFT with both on must sit within noise of mixed-only at the
    same load — that non-regression IS the result, with the spec arm's
    decode acceleration riding along for free.

    Load shape: SPEC_BATCH repetitive long-decode sessions saturate the
    batch (the n-gram proposer's home turf, so verify slices are real),
    then SPEC_CHAT_PROBES short chat prompts arrive serially; each
    probe's TTFT is measured while the sessions keep decoding, and the
    sessions' decode progress per step is reported alongside — the
    composition's actual win is BOTH columns at once (chat TTFT parity
    with mixed-only while the sessions advance accepted+1 tokens per
    step instead of one). The step token budget is sized for the verify
    slices (chat_len + batch*(k+1) — the operator guidance: a budget
    tuned for 1-token decode rows would starve the chunk once rows widen
    to S tokens)."""
    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    pattern = rng.integers(1, 200, 8).tolist()
    sess_len = max(64, min(PROMPT_LEN, 256))
    reps = cdiv(sess_len, len(pattern))
    sess_prompts = [(pattern * reps)[:sess_len] for _ in range(SPEC_BATCH)]
    chat_len = 48
    sess_new, chat_new = 512, 8
    budget = chat_len + SPEC_BATCH * (SPEC_K + 1)
    out = {"sessions": SPEC_BATCH, "chat_probes": SPEC_CHAT_PROBES}

    for label, spec in (("mixed_only", False), ("spec_mixed", True)):
        pages_per_seq = cdiv(sess_len + sess_new + SPEC_K, page) + 2
        cfg = EngineConfig(
            model=get_model_config(model_name).replace(quantization=quant),
            cache=CacheConfig(
                page_size=page,
                num_pages=(SPEC_BATCH + 2) * pages_per_seq + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=SPEC_BATCH + 2, max_prefill_tokens=chat_len,
                decode_priority_token_budget=budget,
                decode_buckets=(1, 2, 4, max(8, SPEC_BATCH + 2)),
                prefill_buckets=(chat_len, 2 * chat_len),
                decode_window=DECODE_WINDOW, mixed_batch_enabled=True,
                spec_decode_enabled=spec, num_speculative_tokens=SPEC_K))
        engine = LLMEngine(cfg, eos_token_id=None)
        sess_params = SamplingParams(max_tokens=sess_new, temperature=0.0)
        chat_params = SamplingParams(max_tokens=chat_new, temperature=0.0)
        # Warmup: one session + one chat probe compile the families.
        engine.add_request("warm-s", list(sess_prompts[0]), sess_params)
        for _ in range(8):
            engine.step()
        engine.add_request("warm-c", rng.integers(1, 200, chat_len).tolist(),
                           chat_params)
        while engine.has_unfinished_requests():
            engine.step()
        # Saturate decode, then probe chat TTFT serially mid-decode.
        for i, p in enumerate(sess_prompts):
            engine.add_request(f"s-{i}", list(p), sess_params)
        for _ in range(SPEC_BATCH + 8):
            engine.step()

        def probe(rid):
            prompt = rng.integers(1, 200, chat_len).tolist()
            t0 = time.perf_counter()
            engine.add_request(rid, prompt, chat_params)
            ttft = None
            while ttft is None and engine.has_unfinished_requests():
                for o in engine.step():
                    if o.request_id == rid and o.new_token_ids:
                        ttft = time.perf_counter() - t0
                        break
            engine.abort_request(rid)
            return ttft if ttft is not None else float("nan")

        # Two unmeasured probes compile the chunk-bearing step families
        # against the NOW-draftable session batch (warm-c above ran before
        # the sessions existed, so the spec×mixed shape first appears
        # here).
        for i in range(2):
            probe(f"warm-probe-{i}")
        # EVERY reported quantity is a measured-window delta over one
        # consistent baseline (warmup + warm probes excluded), and session
        # tokens are read off the Sequence OBJECTS — a session that
        # finishes mid-window keeps its token history, where a
        # running-set re-scan would silently drop it.
        kinds0 = dict(engine.obs.step_kind_counts)
        drafted0 = engine.obs.spec_drafted_tokens
        accepted0 = engine.obs.spec_accepted_tokens
        sess_seqs = [s for s in engine.scheduler.running
                     if s.request_id.startswith("s-")]
        sess_tokens0 = sum(len(s.output_token_ids) for s in sess_seqs)
        t0_probe = time.perf_counter()
        ttfts = [probe(f"chat-{i}") for i in range(SPEC_CHAT_PROBES)]
        probe_wall = time.perf_counter() - t0_probe
        sess_tokens = sum(len(s.output_token_ids)
                          for s in sess_seqs) - sess_tokens0
        kinds = {k: engine.obs.step_kind_counts[k] - kinds0[k]
                 for k in kinds0}
        arm = {
            "chat_ttft_p50_ms": round(_percentile(ttfts, 0.5) * 1e3, 2),
            "mixed_steps": kinds["mixed"] + kinds["spec_mixed"],
            # The throughput half of the composition: how fast the decode
            # sessions advanced WHILE chat probes were in flight
            # (spec×mixed rows commit accepted+1 per step; mixed-only
            # rows commit one).
            "session_tokens_per_sec": (round(sess_tokens / probe_wall, 1)
                                       if probe_wall > 0 else None),
        }
        if spec:
            drafted = engine.obs.spec_drafted_tokens - drafted0
            accepted = engine.obs.spec_accepted_tokens - accepted0
            arm["spec_mixed_steps"] = kinds["spec_mixed"]
            arm["spec_steps"] = kinds["spec"] + kinds["spec_mixed"]
            arm["acceptance_ratio"] = (round(accepted / drafted, 3)
                                       if drafted else None)
        out[label] = arm
        del engine
        gc.collect()
    base = out["mixed_only"]["chat_ttft_p50_ms"]
    out["chat_ttft_spec_over_mixed"] = (
        round(out["spec_mixed"]["chat_ttft_p50_ms"] / base, 3)
        if base else None)
    return out


def _ttft_once(engine, rid, prompt, params) -> float:
    """Submit one request on an idle engine, return its TTFT, drain."""
    t0 = time.perf_counter()
    engine.add_request(rid, prompt, params)
    ttft = None
    while engine.has_unfinished_requests() and ttft is None:
        outs = engine.step()
        now = time.perf_counter()
        for o in outs:
            if o.request_id == rid and o.new_token_ids:
                ttft = now - t0
                break
    engine.abort_request(rid)
    while engine.has_unfinished_requests():
        engine.step()
    return ttft if ttft is not None else float("nan")


def _measure_prefix_reuse(model_name: str, quant, rng) -> dict:
    """prefix_reuse phase (ROADMAP item 2's done-criterion): the
    shared-system-prompt workload that motivates cross-request KV reuse.
    One request at a time on a prefix-caching engine:

    - COLD wave: unique prompts -> every prefix lookup misses, full-prompt
      prefill TTFT.
    - one seeding request with the shared prefix, then the WARM wave:
      requests sharing that page-aligned prefix + a unique tail -> the
      cached pages become chunked-prefill history and only the tail
      prefills, so TTFT collapses toward first-new-token cost.

    Both programs (full prefill, history-chunk) are compiled in a discarded
    warmup pair first — never time XLA compilation. Like the spec phase,
    this builds its own small engine after run_config freed the big one."""
    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    shared_len = max(PROMPT_LEN // page, 1) * page      # page-aligned prefix
    tail = PREFIX_TAIL
    n = PREFIX_REQS
    vocab_cap = 200                                      # safe for any vocab
    max_new = 4
    full_len = shared_len + tail
    # A bucket ladder FINER than the full prompt: a warm request prefills
    # only its tail, and the collapse is only visible if that tail lands in
    # a small compiled bucket instead of padding back up to the cold shape.
    ladder = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)
    top = next((b for b in ladder if b >= full_len), full_len)
    buckets = tuple(b for b in ladder if b < full_len) + (top,)
    pages_per_seq = cdiv(full_len + max_new, page) + 1
    cfg = EngineConfig(
        model=get_model_config(model_name).replace(quantization=quant),
        cache=CacheConfig(
            page_size=page,
            # Pool holds the live request + every cached prompt of the cold
            # wave (the CachingPageAllocator only evicts under pressure).
            num_pages=(2 * n + 4) * pages_per_seq + 1),
        scheduler=SchedulerConfig(
            max_num_seqs=2, max_prefill_tokens=top,
            decode_buckets=(1, 2), prefill_buckets=buckets,
            decode_window=4, mixed_batch_enabled=False,
            enable_prefix_caching=True))
    engine = LLMEngine(cfg, eos_token_id=None)
    params = SamplingParams(max_tokens=max_new, temperature=0.0)

    def prompt_of(prefix_seed: int, tail_seed: int) -> list:
        p_rng = np.random.default_rng(prefix_seed)
        t_rng = np.random.default_rng(tail_seed)
        return (p_rng.integers(1, vocab_cap, shared_len).tolist()
                + t_rng.integers(1, vocab_cap, tail).tolist())

    # Warmup pair: compiles the full-prefill AND the cached-history
    # (chunked) programs; TTFTs discarded.
    _ttft_once(engine, "warm-a", prompt_of(10_000, 1), params)
    _ttft_once(engine, "warm-b", prompt_of(10_000, 2), params)

    pc = engine.scheduler.prefix_cache
    hits0, misses0 = pc.hits, pc.misses
    cold = [_ttft_once(engine, f"cold-{i}", prompt_of(20_000 + i, i), params)
            for i in range(n)]
    _ttft_once(engine, "seed", prompt_of(30_000, 100), params)
    warm = [_ttft_once(engine, f"warm-{i}",
                       prompt_of(30_000, 200 + i), params)
            for i in range(n)]
    cold_p50 = _median([t for t in cold if t == t])
    warm_p50 = _median([t for t in warm if t == t])
    return {
        "n_requests": n,
        "shared_prefix_tokens": shared_len,
        "tail_tokens": tail,
        "ttft_cold_p50_ms": round(cold_p50 * 1e3, 1),
        "ttft_warm_p50_ms": round(warm_p50 * 1e3, 1),
        "warm_over_cold": (round(warm_p50 / cold_p50, 3)
                           if cold_p50 and cold_p50 == cold_p50 else None),
        "cache_hits": pc.hits - hits0,
        "cache_misses": pc.misses - misses0,
    }


def _measure_swap(model_name: str, quant, rng) -> dict:
    """kv_swap phase (ROADMAP item 2's host-offload criterion): a session
    workload oversubscribed ~SWAP_OVERSUB x the device page pool, so the
    scheduler must preempt, A/B'd on identically-seeded engines:

    - swap arm: host-DRAM tier on — victims' committed KV moves to host and
      readmission is a scatter + direct decode resume;
    - recompute arm: single-tier baseline — victims re-prefill from scratch.

    The headline is resumed-session TTFT: the wall gap between a session's
    preemption (its "preempt" trace event — the same clock the step loop's
    token timestamps use) and its NEXT emitted token. Swap replaces the
    re-prefill with a memcpy, so its gap should sit measurably below the
    recompute arm's at >= 2x oversubscription. Wave 1 of each arm is a
    discarded compile warmup (never time XLA compilation)."""
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import (
        kv_cache_bytes_per_page)

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    n = SWAP_SESSIONS
    prompt_len = max(PROMPT_LEN // page, 1) * page
    max_new = SWAP_MAX_NEW
    pages_per_seq = cdiv(prompt_len + max_new, page)
    # Oversubscribed pool: all n sessions need ~SWAP_OVERSUB x what fits.
    num_pages = max(int(n * pages_per_seq / SWAP_OVERSUB), pages_per_seq) + 1
    mcfg = get_model_config(model_name).replace(quantization=quant)
    # Host tier sized to hold every session at once — the phase measures
    # swap value, not host-pool pressure.
    swap_gb = (n * pages_per_seq * kv_cache_bytes_per_page(
        mcfg, CacheConfig(page_size=page)) + (1 << 20)) / (1 << 30)
    buckets = tuple(sorted({1, 2, 4, n // 2, n} - {0}))
    prefill_buckets = tuple(sorted({prompt_len, 2 * prompt_len}))
    out = {}
    for label, gb in (("recompute", 0.0), ("swap", swap_gb)):
        cfg = EngineConfig(
            model=mcfg,
            cache=CacheConfig(page_size=page, num_pages=num_pages,
                              swap_space_gb=gb),
            scheduler=SchedulerConfig(
                max_num_seqs=n, max_prefill_tokens=2 * prompt_len,
                decode_buckets=buckets, prefill_buckets=prefill_buckets,
                decode_window=4, mixed_batch_enabled=False))
        engine = LLMEngine(cfg, eos_token_id=None)
        params = SamplingParams(max_tokens=max_new, temperature=0.0)

        def run_wave(tag: str):
            w_rng = np.random.default_rng(1234)   # same prompts both arms
            for i in range(n):
                engine.add_request(
                    f"{tag}-{i}",
                    w_rng.integers(1, 200, prompt_len).tolist(), params)
            tok_times: dict = {}
            while engine.has_unfinished_requests():
                outs = engine.step()
                now = time.monotonic()     # the trace ring's clock
                for o in outs:
                    if o.new_token_ids:
                        tok_times.setdefault(o.request_id, []).append(now)
            latencies = []
            for e in engine.obs.tracer.events():
                if e.kind == "preempt" and e.request_id.startswith(tag):
                    nxt = [t for t in tok_times.get(e.request_id, ())
                           if t > e.ts]
                    if nxt:
                        latencies.append(nxt[0] - e.ts)
            return latencies

        run_wave("warm")                       # compiles; discarded
        pre0 = dict(engine.scheduler.num_preemptions_by_kind)
        swap0 = dict(engine.obs.swap_pages)    # warm wave swapped too
        t0 = time.perf_counter()
        lat = run_wave("m")
        wall = time.perf_counter() - t0
        kinds = engine.scheduler.num_preemptions_by_kind
        out[label] = {
            "wall_s": round(wall, 3),
            "preemptions": {k: kinds[k] - pre0[k] for k in kinds},
            "resume_ttft_p50_ms": (round(_median(lat) * 1e3, 1)
                                   if lat else None),
            "resumes_observed": len(lat),
        }
        if label == "swap":
            out[label]["swap_out_pages"] = (engine.obs.swap_pages["out"]
                                            - swap0["out"])
            out[label]["swap_in_pages"] = (engine.obs.swap_pages["in"]
                                           - swap0["in"])
        del engine
        gc.collect()
    sw, rc = out["swap"], out["recompute"]
    out["sessions"] = n
    out["oversubscription"] = round(n * pages_per_seq / (num_pages - 1), 2)
    out["resume_ttft_ratio"] = (
        round(sw["resume_ttft_p50_ms"] / rc["resume_ttft_p50_ms"], 3)
        if sw["resume_ttft_p50_ms"] and rc["resume_ttft_p50_ms"] else None)
    out["preemptions"] = {
        "recompute_arm": rc["preemptions"], "swap_arm": sw["preemptions"]}
    return out


def _measure_qos(model_name: str, quant, rng) -> dict:
    """KGCT_BENCH_QOS phase (ROADMAP item 3): multi-tenant overload
    isolation A/B on identically-seeded engines.

    Workload: QOS_BATCH_SEQS batch-tier jobs (long decodes) saturate every
    scheduler seat, with a finished job immediately replaced so the
    pressure never lets up; QOS_CHAT_REQS short interactive requests
    arrive one at a time and their TTFT (add -> first emitted token) is
    measured. QoS OFF, a chat request waits until a whole batch job
    finishes (seat-bound FCFS); QoS ON, the scheduler's priority
    make-room preemption swaps a batch victim out (host KV tier — the
    cheap preemption PR 7 built) and fair-share promotion admits the chat
    request at once. Wave 1 of each arm is a discarded compile warmup.

    The admission block exercises the per-tier ledger: with the batch
    tier's offered load inflated by the deterministic ``tenant_flood``
    chaos site past its max_concurrent budget, batch checks shed 429s
    while interactive checks all admit — the per-tier shed counters must
    attribute the whole overload to the batch tier."""
    from kubernetes_gpu_cluster_tpu.config import QoSTier
    from kubernetes_gpu_cluster_tpu.engine.kv_cache import (
        kv_cache_bytes_per_page)
    from kubernetes_gpu_cluster_tpu.resilience.deadline import (
        AdmissionController)
    from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
    from kubernetes_gpu_cluster_tpu.utils.math import next_power_of_2

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    n_batch = QOS_BATCH_SEQS
    n_chat = QOS_CHAT_REQS
    prompt_len = max(PROMPT_LEN // page, 1) * page
    chat_prompt = page                      # short chat turns
    batch_new, chat_new = QOS_BATCH_MAX_NEW, QOS_CHAT_MAX_NEW
    pages_per_seq = cdiv(prompt_len + batch_new, page)
    # Seats are the bottleneck by construction (max_num_seqs = n_batch);
    # the pool holds every batch job plus a chat request with slack so
    # page pressure never confounds the seat story.
    num_pages = (n_batch + 2) * pages_per_seq + 1
    mcfg = get_model_config(model_name).replace(quantization=quant)
    swap_gb = ((n_batch + 2) * pages_per_seq * kv_cache_bytes_per_page(
        mcfg, CacheConfig(page_size=page)) + (1 << 20)) / (1 << 30)
    tiers = (QoSTier("interactive", weight=4, priority=10,
                     max_concurrent=max(n_chat, 4)),
             QoSTier("batch", weight=1, priority=0, max_concurrent=2))
    buckets = tuple(sorted({1, 2, 4, n_batch, n_batch + 1,
                            next_power_of_2(n_batch + 1)} - {0}))
    prefill_buckets = tuple(sorted({page, prompt_len, 2 * prompt_len}))
    out: dict = {}
    for label in ("qos_off", "qos_on"):
        cfg = EngineConfig(
            model=mcfg,
            cache=CacheConfig(page_size=page, num_pages=num_pages,
                              swap_space_gb=swap_gb),
            scheduler=SchedulerConfig(
                max_num_seqs=n_batch, max_prefill_tokens=2 * prompt_len,
                decode_buckets=buckets, prefill_buckets=prefill_buckets,
                decode_window=4, mixed_batch_enabled=False,
                qos_tiers=tiers if label == "qos_on" else ()))
        engine = LLMEngine(cfg, eos_token_id=None)
        # qos_tier rides the params in BOTH arms: the tier-less arm
        # ignores it (scheduler.qos is None), so the submitted workloads
        # are literally identical.
        batch_params = SamplingParams(max_tokens=batch_new,
                                      temperature=0.0, qos_tier="batch")
        chat_params = SamplingParams(max_tokens=chat_new, temperature=0.0,
                                     qos_tier="interactive")

        def run_wave(tag: str):
            w_rng = np.random.default_rng(97)   # same workload both arms
            nb = 0

            def add_batch_job():
                nonlocal nb
                engine.add_request(
                    f"{tag}-b{nb}",
                    w_rng.integers(1, 200, prompt_len).tolist(),
                    batch_params)
                nb += 1

            for _ in range(n_batch):
                add_batch_job()
            for _ in range(3):                  # batch into steady decode
                if engine.has_unfinished_requests():
                    engine.step()
            ttfts: list = []
            t_add: dict = {}
            added = done = 0
            while done < n_chat:
                if added == done and added < n_chat:
                    # One chat request in flight at a time: each sample
                    # measures admission under full batch saturation.
                    rid = f"{tag}-c{added}"
                    engine.add_request(
                        rid, w_rng.integers(1, 200, chat_prompt).tolist(),
                        chat_params)
                    t_add[rid] = time.monotonic()
                    added += 1
                outs = engine.step()
                now = time.monotonic()
                for o in outs:
                    rid = o.request_id
                    if rid in t_add and o.new_token_ids:
                        ttfts.append(now - t_add.pop(rid))
                    if o.finished:
                        if rid.startswith(f"{tag}-c"):
                            done += 1
                        else:
                            add_batch_job()    # keep the pressure on
            while engine.has_unfinished_requests():
                engine.step()
            return ttfts

        run_wave("warm")                        # compiles; discarded
        t0 = time.perf_counter()
        ttfts = run_wave("m")
        wall = time.perf_counter() - t0
        out[label] = {
            "wall_s": round(wall, 3),
            "chat_ttft_p50_ms": round(_median(ttfts) * 1e3, 1),
            "chat_ttft_p95_ms": round(_percentile(ttfts, 0.95) * 1e3, 1),
            "chat_requests": len(ttfts),
            "preemptions": dict(engine.scheduler.num_preemptions_by_kind),
        }
        if label == "qos_on":
            # Per-tier admission ledger under a deterministic flood: the
            # batch tier's offered load is inflated past its
            # max_concurrent budget; every batch check must shed and
            # every interactive check must admit.
            adm = AdmissionController(engine)
            adm.configure_tiers(tiers, "interactive")
            configure_faults("tenant_flood:value=8")
            try:
                checks = {"interactive": 0, "batch": 0}
                for i in range(12):
                    tier = "batch" if i % 2 else "interactive"
                    checks[tier] += 1
                    adm.check(None, tier=tier)
            finally:
                configure_faults(None)
            out["admission"] = {
                "checks": checks,
                "shed_by_tier": dict(adm.shed_by_tier),
            }
        del engine
        gc.collect()
    on, off = out["qos_on"], out["qos_off"]
    out["batch_seqs"] = n_batch
    out["qos_chat_ttft_protected_ratio"] = (
        round(on["chat_ttft_p95_ms"] / off["chat_ttft_p95_ms"], 3)
        if on["chat_ttft_p95_ms"] and off["chat_ttft_p95_ms"] else None)
    return out


def _measure_router() -> dict:
    """KGCT_BENCH_ROUTER phase: cache-aware fleet routing A/B through the
    real serving stack — N in-process replicas (api_server.build_server on
    real sockets, prefix caching on) behind serving/router.Router, replaying
    a shared-prefix session workload:

    - ROUTER_SESSIONS sessions, each with its own page-aligned shared
      prefix; ROUTER_ROUNDS rounds issue one request per session
      (prefix + a unique tail), sequentially — the steady inflight=0 state
      where least-inflight's tie-break round-robins and scatters sessions.
    - arm "least_inflight": the pre-affinity policy. A session's round-2
      request lands on the OTHER replica (cold: full-prefix prefill).
    - arm "prefix_affinity": bounded-load ring routing on the prompt
      prefix — every round after the first lands on the owner replica
      whose cache holds the prefix (warm: tail-only prefill).

    Both arms run identically-seeded engines and identical prompts; each
    replica is warmed DIRECTLY (bypassing the router) with a discarded
    prefix-reuse pair so the full-prefill AND cached-history programs are
    compiled everywhere before measurement (never time XLA compilation).
    Headline: affinity warm-request TTFT p50 / least-inflight's, plus
    per-replica prefix-cache hit ratios showing locality concentrate."""
    import asyncio

    import aiohttp
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
    from kubernetes_gpu_cluster_tpu.serving.router import Router

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    shared_len = max(PROMPT_LEN // page, 1) * page
    tail = 16
    full_len = shared_len + tail
    vocab_cap = 200
    ladder = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)
    top = next((b for b in ladder if b >= full_len), full_len)
    buckets = tuple(b for b in ladder if b < full_len) + (top,)
    pages_per_seq = cdiv(full_len + 4, page) + 1

    def engine_config():
        return EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(
                page_size=page,
                num_pages=(2 * (ROUTER_SESSIONS + 1) + 4) * pages_per_seq + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=2, max_prefill_tokens=top,
                decode_buckets=(1, 2), prefill_buckets=buckets,
                decode_window=4, mixed_batch_enabled=False,
                enable_prefix_caching=True))

    def prompt_of(prefix_seed: int, tail_seed: int) -> list:
        p_rng = np.random.default_rng(prefix_seed)
        t_rng = np.random.default_rng(tail_seed)
        return (p_rng.integers(1, vocab_cap, shared_len).tolist()
                + t_rng.integers(1, vocab_cap, tail).tolist())

    def scrape(text: str, name: str) -> float:
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.rpartition(" ")[2])
        return 0.0

    async def run_arm(policy: str) -> dict:
        runners, urls = [], []
        for _ in range(ROUTER_REPLICAS):
            srv = build_server(engine_config(), None, "debug-tiny")
            runner = aioweb.AppRunner(srv.build_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            urls.append(f"http://127.0.0.1:{runner.addresses[0][1]}")
        # Affinity window clamped to the shared prefix: with a short
        # KGCT_BENCH_PROMPT the default 32-token window would fold each
        # request's UNIQUE tail into the key, silently un-sticking the
        # sessions and reporting a misleading "affinity does not help".
        router = Router(urls, health_interval_s=9999,
                        routing_policy=policy,
                        affinity_prefix_len=min(32, shared_len))
        rrunner = aioweb.AppRunner(router.build_app())
        await rrunner.setup()
        rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
        await rsite.start()
        router_url = f"http://127.0.0.1:{rrunner.addresses[0][1]}"

        out: dict = {"policy": policy}
        try:
            async with aiohttp.ClientSession() as sess:
                async def complete(base: str, prompt: list) -> float:
                    t0 = time.perf_counter()
                    async with sess.post(
                            f"{base}/v1/completions",
                            json={"prompt": prompt, "max_tokens": 1,
                                  "temperature": 0.0}) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()
                    return time.perf_counter() - t0

                # Direct per-replica warmup (discarded): compiles full
                # prefill, cached-history chunk, and decode everywhere.
                for i, url in enumerate(urls):
                    await complete(url, prompt_of(90_000 + i, 0))
                    await complete(url, prompt_of(90_000 + i, 1))

                before = []
                for url in urls:
                    async with sess.get(f"{url}/metrics") as resp:
                        before.append(await resp.text())

                cold, warm = [], []
                for rnd in range(ROUTER_ROUNDS):
                    for s in range(ROUTER_SESSIONS):
                        dt = await complete(
                            router_url,
                            prompt_of(50_000 + s, 1000 * rnd + s))
                        (cold if rnd == 0 else warm).append(dt)

                per_replica = []
                for i, url in enumerate(urls):
                    async with sess.get(f"{url}/metrics") as resp:
                        text = await resp.text()
                    hits = (scrape(text, "kgct_prefix_cache_hits_total")
                            - scrape(before[i],
                                     "kgct_prefix_cache_hits_total"))
                    misses = (scrape(text, "kgct_prefix_cache_misses_total")
                              - scrape(before[i],
                                       "kgct_prefix_cache_misses_total"))
                    served = (scrape(text, "kgct_requests_total")
                              - scrape(before[i], "kgct_requests_total"))
                    per_replica.append({
                        "requests": int(served),
                        "cache_hits": int(hits),
                        "cache_misses": int(misses),
                        "hit_ratio": (round(hits / (hits + misses), 3)
                                      if hits + misses else None),
                        # The per-replica SLO gauge the fleet autoscaler
                        # reads (one scrape per replica, same surface).
                        "slo_ttft_attainment_ratio": scrape(
                            text, "kgct_slo_ttft_attainment_ratio"),
                    })
                # Fleet-merged trace: ONE download of the router's
                # /debug/trace must hold the router's own spans AND engine
                # lifecycle spans from the replicas, correlated on the
                # router-minted request ids (the acceptance contract; the
                # summary rides the stderr FULL_RESULT, not the headline).
                async with sess.get(f"{router_url}/debug/trace") as resp:
                    tdoc = await resp.json()
                ids_by_pid: dict = {}
                for e in tdoc["traceEvents"]:
                    if e.get("cat") == "request" and e.get("id"):
                        ids_by_pid.setdefault(e["pid"], set()).add(e["id"])
                router_ids = ids_by_pid.get(1, set())
                out["merged_trace"] = {
                    "processes": len({e.get("pid")
                                      for e in tdoc["traceEvents"]}),
                    "router_requests": len(router_ids),
                    "replicas_sharing_ids": sum(
                        1 for pid, ids in ids_by_pid.items()
                        if pid != 1 and ids & router_ids),
                }
                out.update({
                    "ttft_cold_p50_ms": round(_median(cold) * 1e3, 1),
                    "ttft_warm_p50_ms": round(_median(warm) * 1e3, 1),
                    "per_replica": per_replica,
                })
                if policy == "prefix-affinity":
                    reqs = router.affinity_requests_total
                    out["affinity_hit_ratio"] = (
                        round(router.affinity_hits_total / reqs, 3)
                        if reqs else None)
                    out["ring_remaps"] = router.ring_remaps_total
        finally:
            await rrunner.cleanup()
            for runner in runners:
                await runner.cleanup()
        return out

    out: dict = {
        "replicas": ROUTER_REPLICAS,
        "sessions": ROUTER_SESSIONS,
        "rounds": ROUTER_ROUNDS,
        "shared_prefix_tokens": shared_len,
        "tail_tokens": tail,
    }
    for label, policy in (("least_inflight", "least-inflight"),
                          ("prefix_affinity", "prefix-affinity")):
        out[label] = asyncio.run(run_arm(policy))
        gc.collect()
    li, aff = out["least_inflight"], out["prefix_affinity"]
    out["warm_ttft_ratio"] = (
        round(aff["ttft_warm_p50_ms"] / li["ttft_warm_p50_ms"], 3)
        if li["ttft_warm_p50_ms"] else None)
    return out


def _measure_fleet_cache() -> dict:
    """KGCT_BENCH_FLEET_CACHE phase: fleet-wide KV reuse A/B through the
    real serving stack — an OWNER replica whose prefix cache holds each
    session's shared prefix and a NON-OWNER replica the sessions are
    forced onto (requests go DIRECTLY to the non-owner carrying the
    x-kgct-prefix-source hint the router's overflow path would set,
    which also exercises the --peer-pool allowlist):

    - arm "pull" (fleet cache on): the non-owner pulls the owner's cached
      prefix pages over /internal/fetch_prefix, streams them into its own
      cache, and prefills only the unique tail;
    - arm "recompute" (fleet cache off): the hint is ignored and the
      non-owner re-prefills the whole prefix — today's behavior.

    Both arms run identically-seeded engines and identical prompts; both
    replicas are warmed directly (full-prefill + cached-history programs
    compiled everywhere, plus one discarded pulled session in the pull
    arm so the transfer scatter's compile is not timed). Headline:
    pull-arm warm TTFT p50 / recompute-arm's."""
    import asyncio

    import aiohttp
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
    from kubernetes_gpu_cluster_tpu.serving.errors import PREFIX_SOURCE_HEADER

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    shared_len = max(FLEET_SHARED // page, 1) * page
    tail = 16
    full_len = shared_len + tail
    vocab_cap = 200
    ladder = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)
    top = next((b for b in ladder if b >= full_len), full_len)
    buckets = tuple(b for b in ladder if b < full_len) + (top,)
    pages_per_seq = cdiv(full_len + 4, page) + 1

    def engine_config():
        # max_num_seqs also CAPS the page pool (the engine never holds
        # more pages than max_num_seqs full sequences): 8 seats keep the
        # cap above every warmed session's cached chain, so the owner's
        # cache is not evicting session prefixes before their pull.
        return EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(
                page_size=page,
                num_pages=(2 * (FLEET_SESSIONS + 3) + 4) * pages_per_seq + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=8, max_prefill_tokens=top,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=buckets,
                decode_window=4, mixed_batch_enabled=False,
                enable_prefix_caching=True))

    def prompt_of(prefix_seed: int, tail_seed: int) -> list:
        p_rng = np.random.default_rng(prefix_seed)
        t_rng = np.random.default_rng(tail_seed)
        return (p_rng.integers(1, vocab_cap, shared_len).tolist()
                + t_rng.integers(1, vocab_cap, tail).tolist())

    def scrape(text: str, name: str) -> float:
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.rpartition(" ")[2])
        return 0.0

    async def run_arm(fleet_on: bool, integrity: bool = True) -> dict:
        runners = []

        async def serve(**kw):
            srv = build_server(engine_config(), None, "debug-tiny", **kw)
            runner = aioweb.AppRunner(srv.build_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            return f"http://127.0.0.1:{runner.addresses[0][1]}"

        out: dict = {"fleet_cache": fleet_on, "integrity": integrity}
        try:
            owner_url = await serve(fleet_prefix_cache=fleet_on,
                                    integrity_checks=integrity)
            puller_url = await serve(fleet_prefix_cache=fleet_on,
                                     integrity_checks=integrity,
                                     peer_pool=[owner_url])
            async with aiohttp.ClientSession() as sess:
                async def complete(base, prompt, hint=None):
                    headers = ({PREFIX_SOURCE_HEADER: hint} if hint else {})
                    t0 = time.perf_counter()
                    async with sess.post(
                            f"{base}/v1/completions",
                            json={"prompt": prompt, "max_tokens": 1,
                                  "temperature": 0.0},
                            headers=headers) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()
                    return time.perf_counter() - t0

                # Compile warmup on BOTH replicas: full prefill + the
                # cached-history tail chunk (discarded local session).
                for url in (owner_url, puller_url):
                    await complete(url, prompt_of(90_000, 0))
                    await complete(url, prompt_of(90_000, 1))
                # Pull-path warmup (discarded session): the transfer
                # scatter's first compile must not land in a measured TTFT.
                await complete(owner_url, prompt_of(91_000, 0))
                await complete(puller_url, prompt_of(91_000, 1),
                               hint=owner_url)

                # Warm each session's prefix on the OWNER, then force the
                # session's next request onto the NON-owner with the hint.
                warm = []
                for s in range(FLEET_SESSIONS):
                    await complete(owner_url, prompt_of(60_000 + s, 0))
                for s in range(FLEET_SESSIONS):
                    warm.append(await complete(
                        puller_url, prompt_of(60_000 + s, 1000 + s),
                        hint=owner_url))
                async with sess.get(f"{puller_url}/metrics") as resp:
                    text = await resp.text()
                out.update({
                    "warm_ttft_p50_ms": round(_median(warm) * 1e3, 1),
                    "pulls_ok": int(scrape(
                        text,
                        'kgct_fleet_prefix_pulls_total{outcome="ok"}')),
                    "pulls_skipped": int(scrape(
                        text,
                        'kgct_fleet_prefix_pulls_total{outcome="skipped"}')),
                    "pulled_bytes": int(scrape(
                        text, 'kgct_fleet_prefix_bytes_total{dir="pull"}')),
                    "prefix_cache_hit_ratio": scrape(
                        text, "kgct_prefix_cache_hit_ratio"),
                })
        finally:
            for runner in reversed(runners):
                await runner.cleanup()
        return out

    out: dict = {
        "sessions": FLEET_SESSIONS,
        "shared_prefix_tokens": shared_len,
        "tail_tokens": tail,
    }
    # Third arm: the pull path with the wire-integrity layer off — the
    # checksum cost (encode-side CRC folds + decode-side re-verify) is
    # the only difference, so the ratio IS the integrity overhead on the
    # wire path. Droppable: dashboards treat an absent ratio as "not
    # measured", never as 1.0.
    for label, fleet_on, integrity in (("recompute", False, True),
                                       ("pull", True, True),
                                       ("pull_integrity_off", True, False)):
        out[label] = asyncio.run(run_arm(fleet_on, integrity))
        gc.collect()
    pull, rec = out["pull"], out["recompute"]
    out["fleet_prefix_pull_over_recompute_ttft"] = (
        round(pull["warm_ttft_p50_ms"] / rec["warm_ttft_p50_ms"], 3)
        if rec["warm_ttft_p50_ms"] else None)
    off = out["pull_integrity_off"]
    out["kv_integrity_overhead_ratio"] = (
        round(pull["warm_ttft_p50_ms"] / off["warm_ttft_p50_ms"], 3)
        if off["warm_ttft_p50_ms"] else None)
    return out


def _hist_buckets(text: str, family: str, replicas=None) -> dict:
    """Cumulative bucket counts {le: count} for ``family`` summed over the
    router-relabeled per-replica series (all label sets, e.g. the TTFT
    histogram's outcome children), optionally restricted to ``replicas``
    (URLs)."""
    buckets: dict = {}
    prefix = family + "_bucket{"
    for line in text.splitlines():
        if not line.startswith(prefix):
            continue
        labels, _, value = line[len(prefix):].partition("} ")
        kv = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        le = kv.get('le', '"+Inf"').strip('"')
        if replicas is not None and kv.get("replica", "").strip('"') \
                not in replicas:
            continue
        try:
            buckets[le] = buckets.get(le, 0.0) + float(value)
        except ValueError:
            continue
    return buckets


def _hist_delta(before: str, after: str, family: str,
                replicas=None) -> dict:
    """Measured-window bucket deltas {le: after - before} for ``family``,
    keeping buckets whose first sample landed inside the window (absent
    from the before-scrape). One parse per scrape text."""
    after_b = _hist_buckets(after, family, replicas)
    delta = {le: after_b.get(le, 0.0) - n
             for le, n in _hist_buckets(before, family, replicas).items()}
    for le, n in after_b.items():
        delta.setdefault(le, n)
    return delta


def _bucket_quantile(delta: dict, q: float):
    """Quantile (seconds) from cumulative-bucket DELTAS by linear
    interpolation inside the crossing bucket; None on an empty window."""
    def le_key(le):
        return math.inf if le == "+Inf" else float(le)
    items = sorted(delta.items(), key=lambda kv: le_key(kv[0]))
    total = items[-1][1] if items else 0.0
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_n = 0.0, 0.0
    for le, n in items:
        if n >= target:
            hi = le_key(le)
            if hi is math.inf:
                return prev_le
            frac = ((target - prev_n) / (n - prev_n)) if n > prev_n else 1.0
            return prev_le + frac * (hi - prev_le)
        prev_le, prev_n = le_key(le), n
    return prev_le


def _measure_disagg() -> dict:
    """KGCT_BENCH_DISAGG phase: disaggregated prefill/decode A/B through
    the real serving stack on a MIXED workload —

    - arm "colocated": 2 role="both" replicas behind the router; every
      replica interleaves long prefills with its decode steps, so decode
      inter-token latency absorbs the prefill stalls (mixed batching is
      OFF in both arms to expose the full interference that DistServe-
      style disaggregation removes rather than bounds);
    - arm "disagg": 1 role="prefill" + 1 role="decode" replica; the router
      routes completions to the decode pool with an x-kgct-prefill-url
      header, the decode replica pulls the prefilled KV (one contiguous
      buffer) and resumes decode directly — its device steps are decode-
      only, so TPOT stays flat while prefills land elsewhere.

    Workload: DISAGG_DECODE_SESSIONS decode-heavy sessions (short prompt,
    DISAGG_MAX_NEW tokens) run concurrently with DISAGG_PREFILLS long-
    prompt/1-token prefill-heavy requests. Sustained decode TPOT p95 and
    TTFT p50 are read from ONE router scrape per arm (delta of the
    relabeled per-replica histograms over the measured window; the
    prefill-heavy requests emit one token and thus never enter the TPOT
    histogram — the p95 is pure decode-session TPOT). Headline:
    ``disagg_tpot_over_colocated`` = disagg TPOT p95 / colocated's."""
    import asyncio

    import aiohttp
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
    from kubernetes_gpu_cluster_tpu.serving.router import Router

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    short_len = 2 * page
    long_len = 8 * page
    vocab_cap = 200
    ladder = (32, 64, 128, 256, 512, 1024, 2048, 4096)
    top = next((b for b in ladder if b >= long_len), long_len)
    buckets = tuple(b for b in ladder if b < long_len) + (top,)
    pages_per_seq = cdiv(long_len + DISAGG_MAX_NEW + 4, page) + 1

    def engine_config():
        return EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(
                page_size=page,
                num_pages=4 * (DISAGG_DECODE_SESSIONS + DISAGG_PREFILLS)
                * pages_per_seq + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=4, max_prefill_tokens=top,
                decode_buckets=(1, 2, 4), prefill_buckets=buckets,
                decode_window=4, mixed_batch_enabled=False))

    def prompt_of(seed: int, length: int) -> list:
        return np.random.default_rng(seed).integers(
            1, vocab_cap, length).tolist()

    async def run_arm(disagg: bool) -> dict:
        runners = []

        async def serve(role):
            srv = build_server(engine_config(), None, "debug-tiny",
                               role=role)
            runner = aioweb.AppRunner(srv.build_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            return f"http://127.0.0.1:{runner.addresses[0][1]}"

        if disagg:
            prefill_urls = [await serve("prefill")]
            decode_urls = [await serve("decode")]
        else:
            prefill_urls = None
            decode_urls = [await serve("both"), await serve("both")]
        router = Router(decode_urls, health_interval_s=9999,
                        prefill_urls=prefill_urls)
        rrunner = aioweb.AppRunner(router.build_app())
        await rrunner.setup()
        rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
        await rsite.start()
        router_url = f"http://127.0.0.1:{rrunner.addresses[0][1]}"

        out: dict = {"arm": "disagg" if disagg else "colocated",
                     "decode_replicas": decode_urls,
                     "prefill_replicas": prefill_urls or []}
        try:
            async with aiohttp.ClientSession() as sess:
                async def complete(prompt, max_tokens):
                    async with sess.post(
                            f"{router_url}/v1/completions",
                            json={"prompt": prompt,
                                  "max_tokens": max_tokens,
                                  "temperature": 0.0}) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()

                async def scrape_router() -> str:
                    async with sess.get(f"{router_url}/metrics") as resp:
                        return await resp.text()

                async def complete_at(base, prompt, max_tokens):
                    async with sess.post(
                            f"{base}/v1/completions",
                            json={"prompt": prompt,
                                  "max_tokens": max_tokens,
                                  "temperature": 0.0}) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()

                # Warmup, same work in both arms:
                #  1. DIRECT per-replica long+short — every pod compiles
                #     both prompt-length prefill buckets and the decode
                #     window independent of the router's tie-break
                #     rotation (routed warmup would send every long to one
                #     colocated pod and every short to the other, leaving
                #     each a bucket family to JIT inside the measured
                #     window and biasing the A/B);
                #  2. one long+short THROUGH the router — compiles the
                #     disagg handoff gather/scatter pair on both sides of
                #     the seam (plain extra traffic in the colocated arm);
                #  3. a concurrent burst of short sessions — compiles the
                #     larger decode batch buckets at the same concurrency
                #     the measured window drives (the disagg decode pod
                #     takes ALL sessions; a colocated pod roughly half).
                for i, u in enumerate(decode_urls + (prefill_urls or [])):
                    await complete_at(u, prompt_of(9_000 + i, long_len), 1)
                    await complete_at(u, prompt_of(9_100 + i, short_len),
                                      DISAGG_MAX_NEW)
                await complete(prompt_of(9_200, long_len), 1)
                await complete(prompt_of(9_300, short_len), DISAGG_MAX_NEW)
                await asyncio.gather(
                    *(complete(prompt_of(9_400 + s, short_len),
                               DISAGG_MAX_NEW)
                      for s in range(DISAGG_DECODE_SESSIONS)))
                before = await scrape_router()

                t0 = time.perf_counter()

                async def decode_session(s: int):
                    for r in range(DISAGG_DECODE_ROUNDS):
                        await complete(
                            prompt_of(1_000 * s + r, short_len),
                            DISAGG_MAX_NEW)

                async def prefill_storm():
                    for i in range(DISAGG_PREFILLS):
                        await complete(prompt_of(5_000 + i, long_len), 1)

                await asyncio.gather(
                    *(decode_session(s)
                      for s in range(DISAGG_DECODE_SESSIONS)),
                    prefill_storm())
                wall = time.perf_counter() - t0
                after = await scrape_router()

            decode_set = {u for u in decode_urls}
            tpot_d = _hist_delta(before, after, "kgct_tpot_seconds",
                                 decode_set)
            # TTFT from the DECODE pool only, like TPOT: in the disagg arm
            # a handoff request samples TTFT on BOTH pools — partial
            # (arrival-at-prefill -> first token) on the prefill replica,
            # end-to-end (pull + remote prefill + import) on the decode
            # replica — and only the latter compares with the colocated
            # arm's full TTFT.
            ttft_d = _hist_delta(before, after, "kgct_ttft_seconds",
                                 decode_set)
            tpot_p95 = _bucket_quantile(tpot_d, 0.95)
            ttft_p50 = _bucket_quantile(ttft_d, 0.50)
            out.update({
                "wall_s": round(wall, 3),
                "decode_tpot_p95_ms": (round(tpot_p95 * 1e3, 2)
                                       if tpot_p95 is not None else None),
                "ttft_p50_ms": (round(ttft_p50 * 1e3, 2)
                                if ttft_p50 is not None else None),
            })
            if disagg:
                handoffs = 0.0
                for line in after.splitlines():
                    if line.startswith("kgct_disagg_handoffs_total{") \
                            and 'side="import"' in line \
                            and 'outcome="ok"' in line:
                        handoffs += float(line.rpartition(" ")[2])
                out["handoffs_ok"] = int(handoffs)
        finally:
            await rrunner.cleanup()
            for runner in runners:
                await runner.cleanup()
        return out

    out: dict = {
        "decode_sessions": DISAGG_DECODE_SESSIONS,
        "decode_rounds": DISAGG_DECODE_ROUNDS,
        "prefill_requests": DISAGG_PREFILLS,
        "max_new": DISAGG_MAX_NEW,
        "long_prompt_tokens": long_len,
        "short_prompt_tokens": short_len,
    }
    for label, disagg in (("colocated", False), ("disagg", True)):
        out[label] = asyncio.run(run_arm(disagg))
        gc.collect()
    co, dis = out["colocated"], out["disagg"]
    out["tpot_p95_ratio"] = (
        round(dis["decode_tpot_p95_ms"] / co["decode_tpot_p95_ms"], 3)
        if dis.get("decode_tpot_p95_ms") and co.get("decode_tpot_p95_ms")
        else None)
    out["ttft_p50_ratio"] = (
        round(dis["ttft_p50_ms"] / co["ttft_p50_ms"], 3)
        if dis.get("ttft_p50_ms") and co.get("ttft_p50_ms") else None)
    return out


def _measure_drain() -> dict:
    """KGCT_BENCH_DRAIN phase: drain-with-migration vs wait-it-out A/B.

    Both arms run the same oversubscribed streaming session workload (more
    concurrent sessions than one replica's batch seats) over 2 role="both"
    replicas behind the real router, then begin a SIGTERM drain on one
    replica while every session is mid-stream:

    - arm "migrate": the draining replica live-migrates each running
      sequence's committed KV to the router-named peer and severs the
      relay; the router splices the resumed streams (parked-KV import on
      the peer), so the drain completes as soon as the pushes do —
      TRANSFER-bound;
    - arm "wait": the ``migrate_fail`` chaos site fails every export, so
      each sequence degrades to the pre-migration wait-it-out path and the
      drain completes only when the longest in-flight decode does —
      DECODE-bound.

    Reported per arm: drain wall seconds (begin_drain -> drain task done)
    and the count of client streams that still completed end-to-end (the
    survivability contract: BOTH arms must deliver every stream; only the
    drain time differs). Headline ``drain_migrate_over_wait_seconds`` =
    migrate drain seconds / wait drain seconds."""
    import asyncio

    import aiohttp
    from aiohttp import web as aioweb

    from kubernetes_gpu_cluster_tpu.resilience.faults import configure_faults
    from kubernetes_gpu_cluster_tpu.serving.api_server import build_server
    from kubernetes_gpu_cluster_tpu.serving.router import Router

    on_tpu = jax.default_backend() == "tpu"
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    prompt_len = 2 * page
    vocab_cap = 200
    seats = max(2, (DRAIN_SESSIONS + 1) // 2)   # per-replica seats < sessions:
                                                # the post-migration survivor
                                                # is oversubscribed and queues
    ladder = (32, 64, 128, 256, 512, 1024)
    top = next((b for b in ladder if b >= prompt_len), prompt_len)
    buckets = tuple(b for b in ladder if b < prompt_len) + (top,)
    pages_per_seq = cdiv(prompt_len + DRAIN_MAX_NEW + 4, page) + 1

    def engine_config():
        return EngineConfig(
            model=get_model_config("debug-tiny"),
            cache=CacheConfig(page_size=page,
                              num_pages=2 * DRAIN_SESSIONS * pages_per_seq
                              + 1),
            scheduler=SchedulerConfig(
                max_num_seqs=seats, max_prefill_tokens=top,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=buckets,
                decode_window=4, mixed_batch_enabled=False))

    def prompt_of(seed: int) -> list:
        return np.random.default_rng(seed).integers(
            1, vocab_cap, prompt_len).tolist()

    async def run_arm(migrate: bool) -> dict:
        runners, servers = [], []

        async def serve():
            srv = build_server(engine_config(), None, "debug-tiny")
            runner = aioweb.AppRunner(srv.build_app())
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            runners.append(runner)
            servers.append(srv)
            return f"http://127.0.0.1:{runner.addresses[0][1]}"

        urls = [await serve(), await serve()]
        router = Router(urls, health_interval_s=9999)
        rrunner = aioweb.AppRunner(router.build_app())
        await rrunner.setup()
        rsite = aioweb.TCPSite(rrunner, "127.0.0.1", 0)
        await rsite.start()
        router_url = f"http://127.0.0.1:{rrunner.addresses[0][1]}"
        out: dict = {"arm": "migrate" if migrate else "wait"}
        try:
            async with aiohttp.ClientSession() as sess:
                # Warmup: compile the prefill bucket + decode windows on
                # both replicas (direct), then the migration seam's
                # import path stays cold — its cost IS part of the A/B.
                for i, u in enumerate(urls):
                    async with sess.post(
                            f"{u}/v1/completions",
                            json={"prompt": prompt_of(9_000 + i),
                                  "max_tokens": 8,
                                  "temperature": 0.0}) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()

                started = [asyncio.Event() for _ in range(DRAIN_SESSIONS)]

                async def session(s: int) -> bool:
                    """One streamed completion; True iff the client saw a
                    complete stream ([DONE], no error frame)."""
                    saw_done, saw_error = False, False
                    async with sess.post(
                            f"{router_url}/v1/completions",
                            json={"prompt": prompt_of(s),
                                  "max_tokens": DRAIN_MAX_NEW,
                                  "temperature": 0.0,
                                  "stream": True}) as resp:
                        assert resp.status == 200, await resp.text()
                        async for line in resp.content:
                            text = line.decode("utf-8", "replace").strip()
                            if text.startswith("data:"):
                                started[s].set()
                                payload = text[5:].strip()
                                if payload == "[DONE]":
                                    saw_done = True
                                elif '"error"' in payload:
                                    saw_error = True
                    return saw_done and not saw_error

                tasks = [asyncio.create_task(session(s))
                         for s in range(DRAIN_SESSIONS)]
                await asyncio.gather(*(e.wait() for e in started))
                if not migrate:
                    configure_faults("migrate_fail")
                t0 = time.perf_counter()
                drain_task = servers[0].begin_drain()
                assert drain_task is not None
                await drain_task
                out["drain_seconds"] = round(time.perf_counter() - t0, 3)
                complete = await asyncio.gather(*tasks)
                out["complete_streams"] = sum(complete)
                out["sessions"] = DRAIN_SESSIONS
                mig = servers[0].migration.migrations
                out["migrations_push_ok"] = mig.get(("push", "ok"), 0)
                out["migrations_push_fallback"] = mig.get(
                    ("push", "fallback"), 0)
                out["failovers"] = dict(router.failovers_total)
        finally:
            configure_faults(None)
            await rrunner.cleanup()
            for runner in runners:
                await runner.cleanup()
        return out

    out: dict = {"sessions": DRAIN_SESSIONS, "max_new": DRAIN_MAX_NEW,
                 "prompt_tokens": prompt_len, "seats_per_replica": seats}
    for label, migrate in (("wait", False), ("migrate", True)):
        out[label] = asyncio.run(run_arm(migrate))
        gc.collect()
    mig, wait = out["migrate"], out["wait"]
    out["drain_migrate_over_wait_seconds"] = (
        round(mig["drain_seconds"] / wait["drain_seconds"], 3)
        if mig.get("drain_seconds") and wait.get("drain_seconds") else None)
    return out


# --------------------------------------------------------------------------
# Per-config driver
# --------------------------------------------------------------------------

def run_config(model_name: str, quant, batch: int, *, sustained: bool,
               host_rt_s: float, rng, window: int = None, budget: int = None,
               n_windows: int = None, page_slack: int = 3,
               max_new: int = None) -> dict:
    window = window or DECODE_WINDOW
    budget = budget or PREFILL_BUDGET
    n_windows = n_windows or BENCH_WINDOWS
    max_new = max_new or (
        PROMPT_LEN + window * (WARMUP_WINDOWS + n_windows + 4))
    engine = _mk_engine(model_name, quant, batch, max_new, window, budget,
                        page_slack)
    vocab = engine.config.model.vocab_size

    # Warmup: compile prefill + greedy decode programs.
    _add_batch(engine, rng, vocab, "warm", batch, max_new)
    while engine.scheduler.waiting:
        engine.step()
    for _ in range(WARMUP_WINDOWS):
        engine.step()
    if MIXED_BATCH and batch > 1:
        # Compile the MIXED step program at the sustained-phase shape (one
        # fresh prompt riding a near-full decode batch) so its first-use
        # XLA compile cannot land inside the measured load phases and
        # poison the TTFT percentiles the mixing exists to improve. One
        # warm seat is freed first: a final chunk is only admitted when a
        # max_num_seqs seat is open, which is also the only regime where
        # sustained-phase mixing fires. Up to 3 steps: one drains the
        # in-flight decode chain, one runs the mixed step.
        engine.abort_request("warm-0")
        _add_batch(engine, rng, vocab, "warmmix", 1, max_new)
        for _ in range(3):
            if engine.scheduler.waiting:
                engine.step()
        engine.abort_request("warmmix-0")
    _drain(engine, "warm", batch)

    prefill, live_tag = _measure_prefill_ttft(
        engine, rng, vocab, batch, max_new, host_rt_s)
    greedy_rate = _measure_decode(engine, n_windows)
    # Mid-measurement decode context for the roofline. The measured batch is
    # FRESH (last prefill trial): one priming window + half the measured
    # windows — the warmup batch was a different, drained batch.
    ctx_mid = PROMPT_LEN + window * (1 + n_windows // 2)
    _drain(engine, live_tag, batch)

    sampled_rate = (_measure_sampled_decode(engine, rng, vocab, batch, max_new)
                    if SAMPLED_WINDOWS > 0 else float("nan"))

    mcfg = engine.config.model
    acct = _roofline(mcfg, quant, batch, ctx_mid)
    util = _utilization(acct, greedy_rate, batch)
    param_bytes, matmul_bytes = _param_bytes(engine.params)
    # Prefill roofline at the measured operating point: one budget-bounded
    # ragged step (the whole fresh batch when it fits the budget). The
    # measured rate's utilization against the compute bound is prefill's
    # "mfu" — the TTFT arithmetic target.
    pf_tokens = min(budget, batch * PROMPT_LEN)
    pf = _roofline_prefill(mcfg, quant, pf_tokens)
    pf_rate = prefill["prefill_tokens_per_sec"]
    if pf_rate and pf_rate == pf_rate:
        pf["prefill_mfu"] = round(
            pf_rate * pf["flops_per_token"] / (CHIP_TFLOPS_BF16 * 1e12), 4)
        pf["measured_step_ms"] = round(pf_tokens / pf_rate * 1e3, 1)
    # Observability readout: median queue/prefill/first-fetch TTFT split and
    # the per-phase step-time attribution accumulated over the whole run —
    # a TTFT or tok/s regression in a future round decomposes into a phase
    # delta instead of a guess.
    ttft_decomp = engine.obs.ttft_decomposition()
    phase_breakdown = engine.obs.phases.breakdown()
    sampled_ratio = engine.obs.sampled_decode_ratio()
    result = {
        "model": model_name,
        "quantization": quant,
        "batch": batch,
        "decode_window": window,
        "prefill_budget": budget,
        "decode_tokens_per_sec": round(greedy_rate, 1),
        "decode_tokens_per_sec_sampled": (round(sampled_rate, 1)
                                          if sampled_rate == sampled_rate
                                          else None),
        "sampled_over_greedy": (round(sampled_rate / greedy_rate, 3)
                                if sampled_rate == sampled_rate else None),
        # Engine-side counterpart of sampled_over_greedy, accumulated over
        # ALL decode steps of the run INCLUDING the sampled program's compile
        # window (so it reads low here; in a long-running server, where
        # compiles amortize to nothing, the kgct_sampled_decode_ratio gauge
        # converges on the true ratio). The regression guard is
        # sampled_over_greedy above, measured post-warmup.
        "sampled_decode_ratio_obs": (round(sampled_ratio, 3)
                                     if sampled_ratio is not None else None),
        **prefill,
        "ttft_decomposition": ttft_decomp,
        "step_phase_breakdown": phase_breakdown,
        "mixed_batch": MIXED_BATCH,
        # Buffer-size accounting over the UPLOADED params pytree (real
        # device buffer bytes, not modeled): the packed-int4 evidence that
        # no dequantized weight copy was materialized — matmul_weight_bytes
        # under int4 is ~0.53x the int8 figure, and a dequantized [in, out]
        # copy anywhere would show up as a ~2x jump.
        "param_bytes": param_bytes,
        "matmul_weight_bytes": matmul_bytes,
        "roofline": {
            "chip": {"hbm_gbps_peak": CHIP_HBM_GBPS,
                     "tflops_bf16_peak": CHIP_TFLOPS_BF16},
            "decode_ctx_modeled": ctx_mid,
            **{k: acct[k] for k in ("weight_stream_bytes", "kv_bytes_per_step",
                                    "flops_per_token")},
            **util,
            "prefill": pf,
        },
    }
    if sustained and greedy_rate > 0:
        rate_rps = LOAD_UTILIZATION * greedy_rate / LOAD_MAX_NEW
        # Reset the decomposition deques so the sustained phase's split is
        # not diluted by fresh-batch samples — under load, queue wait is the
        # north-star suspect and must be attributed on its own.
        for dq in (engine.obs.ttft_queue_s, engine.obs.ttft_prefill_s,
                   engine.obs.ttft_fetch_s):
            dq.clear()
        kinds_before = dict(engine.obs.step_kind_counts)
        result["sustained_load"] = _measure_sustained(
            engine, rng, vocab, batch, rate_rps)
        result["sustained_load"]["ttft_decomposition"] = (
            engine.obs.ttft_decomposition())
        # Windowed mixed-step ratio for THIS phase (the whole-run gauge is
        # diluted by the fresh-batch phases, which rarely mix).
        deltas = {k: engine.obs.step_kind_counts[k] - kinds_before[k]
                  for k in kinds_before}
        total = sum(deltas.values())
        result["sustained_load"]["mixed_step_ratio"] = (
            round(deltas["mixed"] / total, 3) if total else None)
        over_rps = OVERLOAD_UTILIZATION * greedy_rate / LOAD_MAX_NEW
        # Budget floor: 2x the measured fresh-batch TTFT p50. Admission
        # control sheds QUEUE wait; it cannot (and should not) shed the
        # irreducible prefill compute — a budget below the empty-engine TTFT
        # (e.g. the CPU debug config, where one padded prefill step is
        # seconds) would just report 100% violations of an unachievable bar.
        floor = prefill["ttft_p50_ms"]
        budget_ms = (max(OVERLOAD_TTFT_BUDGET_MS, 2.0 * floor)
                     if floor == floor else OVERLOAD_TTFT_BUDGET_MS)
        result["overload"] = _measure_overload(
            engine, rng, vocab, over_rps, budget_ms)
    # Whole-run mixed-step ratio, read LAST so the sustained/overload phases
    # (where mixing actually engages) are included.
    ratio = engine.obs.mixed_step_ratio()
    result["mixed_step_ratio"] = (round(ratio, 3) if ratio is not None
                                  else None)
    del engine
    gc.collect()
    return result


def _param_bytes(params) -> tuple:
    """(total params pytree bytes, QUANT_LAYER_KEYS+lm_head matmul bytes
    incl. scales) as actually uploaded — sizes come from the live arrays.
    tests/test_quant.py calls this same accounting for its 0.55x A/B, so
    the bench report and the test pin cannot drift."""
    from kubernetes_gpu_cluster_tpu.ops.quant import QUANT_LAYER_KEYS

    total = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    matmul = 0
    layers = params["layers"]
    for key in QUANT_LAYER_KEYS + ("lm_head",):
        store = params if key == "lm_head" else layers
        for k in (key, key + "_scale"):
            if k in store:
                matmul += store[k].size * store[k].dtype.itemsize
    return int(total), int(matmul)


def assemble_output(results: list[dict], backend: str) -> dict:
    """Fold per-config results into the single driver-facing JSON object.

    Pure (no I/O) so tests can round-trip it through ``json.loads`` — r5's
    official record has ``"parsed": null`` because the result line never made
    it through the driver's parser; the assembly and the emission are now
    separately guaranteed (see ``emit_result``)."""
    primary = results[-1]
    bar = A100_VLLM_TOKS_PER_S.get(primary["model"])
    return {
        "metric": (f"decode_tokens_per_sec_per_chip[{primary['model']}"
                   f"{',' + primary['quantization'] if primary['quantization'] else ''}"
                   f",B={primary['batch']},ctx={PROMPT_LEN}]"),
        "value": primary["decode_tokens_per_sec"],
        "unit": "tokens/s/chip",
        "vs_baseline": (round(primary["decode_tokens_per_sec"] / bar, 3)
                        if bar else None),
        "backend": backend,
        # vs_baseline is normalized against a SELF-CHOSEN constant (the
        # reference publishes no numbers): representative single-A100 vLLM
        # decode throughput for this model class.
        "baseline_bar": {"value": bar,
                         "source": ("chosen constant (A100 vLLM class bar)"
                                    if bar else "no bar defined for model")},
        "decode_window": primary["decode_window"],
        "prefill_budget": primary["prefill_budget"],
        # The primary config's TTFT decomposition (queue / prefill /
        # first-step fetch medians) surfaced top-level for the driver.
        "ttft_decomposition": primary.get("ttft_decomposition"),
        "sampled_over_greedy": primary.get("sampled_over_greedy"),
        "mixed_batch": primary.get("mixed_batch"),
        # Speculative phase headlines (full block in
        # configs[-1].speculative): n-gram acceptance, and the draft-model
        # arm's decode throughput over the n-gram arm's (CPU default pairs
        # the target with an oracle same-arch draft — machinery
        # validation; the production ratio needs a real small draft on
        # chip, ROADMAP 1(b)).
        "spec_acceptance_ratio": (primary.get("speculative", {})
                                  .get("spec", {}).get("acceptance_ratio")),
        "spec_draft_over_ngram_speedup": (
            primary.get("speculative", {})
            .get("spec_draft_over_ngram_speedup")),
        # Prefix-reuse phase headline: warm-prefix TTFT as a fraction of
        # cold TTFT (full block in configs[-1].prefix_reuse).
        "prefix_warm_over_cold_ttft": (primary.get("prefix_reuse", {})
                                       .get("warm_over_cold")),
        # KV-swap phase headlines: resumed-session TTFT under swap as a
        # fraction of recompute-preemption, and the per-arm preemption
        # counts (full block in configs[-1].kv_swap).
        "swap_resume_over_recompute_ttft": (primary.get("kv_swap", {})
                                            .get("resume_ttft_ratio")),
        "preemptions": primary.get("kv_swap", {}).get("preemptions"),
        # Multi-tenant QoS phase headline: chat p95 TTFT under batch
        # saturation with QoS tiers on as a fraction of the tier-less
        # engine's (< 1 = interactive traffic protected; full A/B block
        # incl. per-tier shed attribution in configs[-1].qos).
        "qos_chat_ttft_protected_ratio": (
            primary.get("qos", {}).get("qos_chat_ttft_protected_ratio")),
        # Fleet-routing phase headline: warm-request TTFT through the
        # prefix-affinity router as a fraction of least-inflight's (full
        # A/B block in configs[-1].router_affinity).
        "router_affinity_warm_over_li_ttft": (
            primary.get("router_affinity", {}).get("warm_ttft_ratio")),
        # Fleet-cache phase headline: warm TTFT on a NON-owner replica
        # with the prefix pulled from the ring owner's cache as a
        # fraction of recomputing it (< 1 = remote KV reuse beats
        # re-prefill; full A/B block in configs[-1].fleet_cache).
        "fleet_prefix_pull_over_recompute_ttft": (
            primary.get("fleet_cache", {})
            .get("fleet_prefix_pull_over_recompute_ttft")),
        # Wire-integrity headline: pull-arm warm TTFT with the per-page
        # checksum layer ON as a fraction of the same pull with it OFF
        # (~1.0 = the CRC folds and import-seam re-verify are in the
        # noise; the A/B's third arm in configs[-1].fleet_cache).
        "kv_integrity_overhead_ratio": (
            primary.get("fleet_cache", {})
            .get("kv_integrity_overhead_ratio")),
        # Disaggregation phase headline: sustained decode TPOT p95 through
        # the role-split prefill/decode topology as a fraction of the
        # colocated topology's, from one router scrape per arm (full A/B
        # block in configs[-1].disagg).
        "disagg_tpot_over_colocated": (
            primary.get("disagg", {}).get("tpot_p95_ratio")),
        # Drain phase headline: drain wall seconds with live KV migration
        # as a fraction of the wait-it-out drain's, same oversubscribed
        # streaming workload, every client stream delivered in both arms
        # (full A/B block in configs[-1].drain).
        "drain_migrate_over_wait_seconds": (
            primary.get("drain", {}).get("drain_migrate_over_wait_seconds")),
        # SLO headline: fraction of the overload phase's admitted requests
        # whose TTFT met the admission budget — the attainment read
        # BENCH_r06 captures alongside raw TTFT (full block in
        # configs[-1].overload).
        "slo_ttft_attainment_ratio": (
            primary.get("overload", {}).get("slo_ttft_attainment_ratio")),
        "configs": results,
    }


def parse_result_line(stdout_text: str) -> dict:
    """Parse a bench run's result from its captured stdout — the inverse of
    ``emit_result`` and the ONLY supported way to consume a transcript.
    Takes the last non-empty line (trailing whitespace/newlines tolerated;
    any amount of earlier noise ignored) and json.loads it, raising
    ValueError with context instead of returning None — the r5 official
    record landed ``"parsed": null`` because a driver-side parser failed
    silently."""
    lines = [ln for ln in stdout_text.splitlines() if ln.strip()]
    if not lines:
        raise ValueError("empty bench stdout: no result line to parse")
    last = lines[-1].strip()
    try:
        out = json.loads(last)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"last stdout line is not the bench result JSON "
            f"(contract: see bench.py --help): {last[:200]!r}") from e
    if not isinstance(out, dict):
        raise ValueError(f"bench result line parsed to {type(out).__name__}, "
                         "expected a JSON object")
    return out


def build_arg_parser() -> argparse.ArgumentParser:
    """--help documents the stdout contract; configuration itself stays on
    KGCT_BENCH_* env vars (listed here) so the driver's invocation is just
    ``python bench.py``."""
    p = argparse.ArgumentParser(
        prog="bench.py",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description=(
            "Serving benchmark: prefill/TTFT, greedy+sampled decode, "
            "roofline (decode + prefill), sustained-load and overload "
            "phases.\n\n" + OUTPUT_CONTRACT),
        epilog=(
            "Configuration (env vars): KGCT_BENCH_MODEL, KGCT_BENCH_QUANT, "
            "KGCT_BENCH_BATCH, KGCT_BENCH_WINDOW, KGCT_BENCH_PREFILL_BUDGET, "
            "KGCT_BENCH_WINDOWS, KGCT_BENCH_SAMPLED_WINDOWS, "
            "KGCT_BENCH_LOAD_REQS, KGCT_BENCH_LOAD_UTIL, "
            "KGCT_BENCH_OVERLOAD_UTIL, KGCT_BENCH_OVERLOAD_REQS, "
            "KGCT_BENCH_TTFT_BUDGET_MS, KGCT_BENCH_MIXED (1=stall-free "
            "mixed prefill/decode batching, default on; 0=legacy "
            "prefill-else-decode), KGCT_BENCH_SPEC (1=speculative-decoding "
            "phase on a repetitive-suffix workload, default on; 0=skip), "
            "KGCT_BENCH_SPEC_K, KGCT_BENCH_SPEC_BATCH, "
            "KGCT_BENCH_SPEC_MAX_NEW, KGCT_BENCH_SPEC_DRAFT (draft-model "
            "preset for the two-model arm; default: the target preset at "
            "the same seed — an oracle draft), KGCT_BENCH_SPEC_MIXED "
            "(1=spec×mixed chat-TTFT composition arm, default on; "
            "0=skip), KGCT_BENCH_SPEC_CHAT_PROBES, "
            "KGCT_BENCH_PREFIX (1=prefix-reuse "
            "phase: cold vs warm shared-prefix TTFT on a prefix-caching "
            "engine, default on; 0=skip), KGCT_BENCH_PREFIX_REQS, "
            "KGCT_BENCH_PREFIX_TAIL, KGCT_BENCH_SWAP (1=kv-swap phase: "
            "oversubscribed session workload, swap-preemption vs "
            "recompute-preemption A/B, default on; 0=skip), "
            "KGCT_BENCH_SWAP_SESSIONS, KGCT_BENCH_SWAP_OVERSUB, "
            "KGCT_BENCH_SWAP_MAX_NEW, KGCT_BENCH_QOS (1=multi-tenant QoS "
            "phase: chat TTFT under batch saturation, tiers on/off A/B on "
            "identically-seeded engines + per-tier shed attribution under "
            "tenant_flood, default on; 0=skip), KGCT_BENCH_QOS_BATCH, "
            "KGCT_BENCH_QOS_CHAT_REQS, KGCT_BENCH_QOS_BATCH_MAX_NEW, "
            "KGCT_BENCH_QOS_CHAT_MAX_NEW, KGCT_BENCH_ROUTER (1=fleet-routing "
            "phase: shared-prefix session workload through the real router "
            "over in-process replicas, least-inflight vs prefix-affinity "
            "A/B, default on; 0=skip), KGCT_BENCH_ROUTER_REPLICAS, "
            "KGCT_BENCH_ROUTER_SESSIONS, KGCT_BENCH_ROUTER_ROUNDS, "
            "KGCT_BENCH_FLEET_CACHE (1=fleet-cache phase: shared-prefix "
            "sessions forced onto a non-owner replica, prefix PULL from "
            "the owner's cache vs full recompute A/B on identically-"
            "seeded replica pairs, default on; 0=skip), "
            "KGCT_BENCH_FLEET_SESSIONS, KGCT_BENCH_FLEET_SHARED, "
            "KGCT_FLEET_BW_GBPS, KGCT_FLEET_FLOPS, "
            "KGCT_BENCH_DISAGG (1=disaggregated prefill/decode phase: "
            "role-split 1 prefill + 1 decode replica with KV-page handoff "
            "vs 2 colocated replicas on a mixed long-prefill/long-decode "
            "workload, sustained decode TPOT p95 + TTFT from one router "
            "scrape per arm, default on; 0=skip), "
            "KGCT_BENCH_DISAGG_SESSIONS, KGCT_BENCH_DISAGG_ROUNDS, "
            "KGCT_BENCH_DISAGG_PREFILLS, KGCT_BENCH_DISAGG_MAX_NEW, "
            "KGCT_BENCH_DRAIN (1=session-survivability phase: "
            "drain-with-live-KV-migration vs wait-it-out drain A/B on an "
            "oversubscribed streaming workload through the router, "
            "default on; 0=skip), KGCT_BENCH_DRAIN_SESSIONS, "
            "KGCT_BENCH_DRAIN_MAX_NEW, "
            "KGCT_BENCH_PROMPT, KGCT_BENCH_PAGE, "
            "KGCT_CHIP_HBM_GBPS, KGCT_CHIP_TFLOPS_BF16. KGCT_BENCH_QUANT "
            "accepts int8 or int4 (the W4A16 dequant-fused path)."))
    return p


# Headline blocks droppable (in order) when the result line must shrink
# further than losing "configs" — the primary metric/value/unit always stay.
_DROPPABLE_HEADLINE = ("ttft_decomposition", "baseline_bar", "mixed_batch",
                       "sampled_over_greedy", "spec_acceptance_ratio",
                       "spec_draft_over_ngram_speedup",
                       "prefix_warm_over_cold_ttft",
                       "swap_resume_over_recompute_ttft", "preemptions",
                       "qos_chat_ttft_protected_ratio",
                       "router_affinity_warm_over_li_ttft",
                       "fleet_prefix_pull_over_recompute_ttft",
                       "kv_integrity_overhead_ratio",
                       "disagg_tpot_over_colocated",
                       "drain_migrate_over_wait_seconds",
                       "slo_ttft_attainment_ratio",
                       "decode_window", "prefill_budget", "vs_baseline")


def compact_result(out: dict, limit: int = RESULT_LINE_MAX) -> dict:
    """Shrink ``out`` until its JSON line fits ``limit`` bytes (the driver
    keeps only a stdout tail — an oversized line gets its HEAD cut off and
    parses to nothing, the BENCH_r05 "parsed": null failure mode). Degrades
    in stages, never fails: drop "configs" (the caller preserves it on
    stderr), then droppable headline blocks, and as a last resort a
    minimal bounded {metric, value, unit} record — a shrunk result always
    beats a decapitated or absent one."""
    line = json.dumps(out)
    if len(line) <= limit:
        return out
    slim = dict(out)
    slim.pop("configs", None)
    slim["configs_on_stderr"] = True
    for key in _DROPPABLE_HEADLINE:
        if len(json.dumps(slim)) <= limit:
            return slim
        slim.pop(key, None)
    if len(json.dumps(slim)) <= limit:
        return slim
    return {"metric": str(out.get("metric"))[:256], "value": out.get("value"),
            "unit": out.get("unit"), "configs_on_stderr": True}


def emit_result(out: dict) -> None:
    """Emit the result as the GUARANTEED last stdout line: json.dumps with
    no embedded newlines AND no more than RESULT_LINE_MAX bytes (a tail
    capture must never decapitate it — see compact_result), everything
    previously buffered flushed first, one write, one flush. All framework
    logging already goes to stderr (utils/logging.py); anything a library
    printed earlier is flushed ahead of the result so interleaving cannot
    split the line. When the full result exceeds the bound, it is emitted
    intact on stderr as a FULL_RESULT line first."""
    slim = compact_result(out)
    if slim is not out:
        sys.stderr.write("FULL_RESULT: " + json.dumps(out) + "\n")
    line = json.dumps(slim)
    # Explicit check, not assert (python -O must not strip the guarantee);
    # unreachable — compact_result's minimal fallback is bounded — but if
    # an invariant ever breaks, fail LOUD before a decapitated record can
    # masquerade as a parse bug downstream.
    if "\n" in line or len(line) > RESULT_LINE_MAX:
        raise RuntimeError(
            f"bench result line violates the stdout contract "
            f"({len(line)} bytes > {RESULT_LINE_MAX} or embedded newline)")
    sys.stderr.flush()
    sys.stdout.flush()
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def main() -> None:
    build_arg_parser().parse_args()   # --help / reject unknown args
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    rng = np.random.default_rng(0)

    if os.environ.get("KGCT_BENCH_MODEL"):
        # Explicit single-config mode (A/B runs, other model families).
        batch = int(os.environ.get("KGCT_BENCH_BATCH",
                                   32 if on_tpu else 8))
        configs = [dict(model_name=os.environ["KGCT_BENCH_MODEL"],
                        quant=os.environ.get("KGCT_BENCH_QUANT") or None,
                        batch=batch, sustained=True)]
    elif on_tpu:
        # Default driver suite: continuity line first (its engine is small),
        # then the 8B int8 r1-r5 line as the quant-ladder A/B, then the
        # PRIMARY 8B int4 config (BASELINE config 2, W4A16) with the
        # sustained-load phase. 8B decode is weight-streaming-bound, so
        # tokens/step scale with batch until HBM runs out; the r5 batch
        # ladder (interleaved probes): B=32 2335 -> B=48 3027 -> B=56 3335
        # -> B=64 3650 tok/s median; B=72 flat (3634), B=80/B=64-at-5-pages
        # OOM by ~1 MB. The fit is an EXACTLY-4-page zero-slack pool
        # (prompt 128 + max_new 384 = 512 tokens/seq; a non-dividing
        # max_new would floor to an under-provisioned pool) + W=28 so 13
        # windows fit the 384-token budget. Slack-0 only risks a graceful
        # chain break at the request tail. r4's +3-slack B=48 OOM'd 17.25G.
        # int4 packs the weight stream to ~0.53x int8 (roofline
        # weight_stream_bytes), so the same B=64 shape should land
        # ~1.5-1.8x the int8 decode rate; it also frees ~3.5 GB of HBM —
        # a B>64 int4 ladder probe is the natural next capture. tinyllama
        # runs twice: B=64 is the r1-r4 continuity line, B=256 the
        # batch-optimal point (same weight-amortization ladder as 8B: 9.9k
        # -> 13.8k (B=128) -> 15.4k (192) -> 16.2k (256) tok/s; B=320
        # fails compile). Larger batches trade fresh-batch TTFT for
        # throughput — both points are reported.
        configs = [dict(model_name="tinyllama-1.1b", quant=None,
                        batch=int(os.environ.get("KGCT_BENCH_BATCH", 64)),
                        sustained=False),
                   dict(model_name="tinyllama-1.1b", quant=None, batch=256,
                        sustained=False, n_windows=9),  # 11-page pool fit
                   dict(model_name="llama-3-8b", quant="int8", batch=64,
                        sustained=False, window=28, budget=2048, n_windows=9,
                        page_slack=0, max_new=384),
                   dict(model_name="llama-3-8b", quant="int4", batch=64,
                        sustained=True, window=28, budget=2048, n_windows=9,
                        page_slack=0, max_new=384)]
    else:
        configs = [dict(model_name="debug-tiny", quant=None,
                        batch=int(os.environ.get("KGCT_BENCH_BATCH", 8)),
                        sustained=True)]

    host_rt_s = _measure_host_rt_s()
    results = [run_config(host_rt_s=host_rt_s, rng=rng, **c) for c in configs]
    if SPEC_BENCH:
        # Speculative phase rides the PRIMARY config's model; it builds its
        # own (small-batch) engines, after run_config freed the big one.
        primary = configs[-1]
        results[-1]["speculative"] = _measure_spec(
            primary["model_name"], primary.get("quant"), rng)
    if PREFIX_BENCH:
        # Prefix-reuse phase: same pattern — own small engine, primary model.
        primary = configs[-1]
        results[-1]["prefix_reuse"] = _measure_prefix_reuse(
            primary["model_name"], primary.get("quant"), rng)
    if SWAP_BENCH:
        # KV-swap phase: same pattern — own small oversubscribed engines.
        primary = configs[-1]
        results[-1]["kv_swap"] = _measure_swap(
            primary["model_name"], primary.get("quant"), rng)
    if QOS_BENCH:
        # Multi-tenant QoS phase: chat-vs-batch overload isolation A/B on
        # identically-seeded engines (own small engines, primary model).
        primary = configs[-1]
        results[-1]["qos"] = _measure_qos(
            primary["model_name"], primary.get("quant"), rng)
    if ROUTER_BENCH:
        # Fleet-routing phase: in-process multi-replica A/B through the
        # real router (always debug-tiny engines; see _measure_router).
        results[-1]["router_affinity"] = _measure_router()
    if FLEET_BENCH:
        # Fleet-cache phase: shared-prefix sessions forced onto a
        # non-owner replica, prefix pull vs full recompute (always
        # debug-tiny engines; see _measure_fleet_cache).
        results[-1]["fleet_cache"] = _measure_fleet_cache()
    if DISAGG_BENCH:
        # Disaggregation phase: role-split prefill/decode pools with KV
        # handoff vs colocated replicas (always debug-tiny engines; see
        # _measure_disagg).
        results[-1]["disagg"] = _measure_disagg()
    if DRAIN_BENCH:
        # Session-survivability phase: drain-with-migration vs wait-it-out
        # on an oversubscribed streaming workload (always debug-tiny
        # engines; see _measure_drain).
        results[-1]["drain"] = _measure_drain()
    emit_result(assemble_output(results, backend))


if __name__ == "__main__":
    main()
