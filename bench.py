"""Serving throughput benchmark — prints ONE JSON line for the driver.

Metric: steady-state decode tokens/sec/chip on TinyLlama-1.1B (BASELINE
config 1's model) under continuous batching on whatever backend is default
(the driver runs this on the real TPU chip).

Measurement discipline (round-1 review finding: the old prefill figure timed
XLA compilation): everything is measured AFTER a warmup phase that triggers
every jit compile (prefill buckets + decode window program). TTFT is the
host-observed time from request submission to its first sampled token for a
fresh batch admitted post-warmup — p50 over the batch, the north-star's
"p50 TTFT under continuous batching" (BASELINE.md).

vs_baseline: the reference publishes no numbers (BASELINE.md "published: {}");
the north star is ">= A100-class throughput per chip". We normalize against
A100_VLLM_TOKS_PER_S, a representative vLLM decode throughput for this model
class on one A100 at the same batch size.

Note on the bench fabric: the TPU chip in this environment is tunnel-attached
with a ~110 ms host<->device round trip. The engine hides it with speculative
decode-window chaining (engine.step dispatches window w+1 before fetching w),
so steady-state decode throughput reflects the chip, not the tunnel; TTFT and
prefill throughput unavoidably include tunnel round trips.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

from kubernetes_gpu_cluster_tpu.config import (
    CacheConfig, EngineConfig, SchedulerConfig, get_model_config)
from kubernetes_gpu_cluster_tpu.engine import LLMEngine, SamplingParams

# SELF-CHOSEN comparison bar, not a measured or published number: the
# reference publishes no benchmarks, so vs_baseline normalizes against a
# representative single-A100 vLLM decode throughput per model class (batch
# ~64). Labeled as such in the output ("baseline_bar").
A100_VLLM_TOKS_PER_S = {
    "tinyllama-1.1b": 6000.0,   # ~1B class
    "debug-tiny": 6000.0,       # CPU smoke path, ~1B bar for continuity
    "llama-3-8b": 1500.0,       # 8B class (BASELINE.json config 2)
    "llama-3-70b": 200.0,       # 70B class, per-chip share of an 8xA100 node
    "mixtral-8x7b": 800.0,      # MoE 47B-total/13B-active class
}

import os

BATCH = int(os.environ.get("KGCT_BENCH_BATCH", 64))
PROMPT_LEN = int(os.environ.get("KGCT_BENCH_PROMPT", 128))
# None = the engine's backend-derived page size (128 on TPU, 16 on CPU), so
# the bench measures the SHIPPED default config.
PAGE = (int(os.environ["KGCT_BENCH_PAGE"])
        if os.environ.get("KGCT_BENCH_PAGE") else None)
# Substeps per XLA program. Re-tuned in r4 after the kernel optimizations
# (global-stream decode prefetch + segment-window prefill) shortened the
# per-substep device time: at matched token budgets W=48 beat W=32 in every
# interleaved pair (11.0-11.3k vs 7.4-9.6k tok/s) — the fixed ~110 ms
# per-window tunnel round trip amortizes worse once substeps got faster.
# W=64 measured ~W=48. (r3 had found 32 > 64 with the slower kernel.)
DECODE_WINDOW = int(os.environ.get("KGCT_BENCH_WINDOW", 48))
# Prefill token budget per step. 4096 (2 steps for the 64x128 batch) is the
# measured operating point AFTER the segment-aware k-window upgrade to the
# flash prefill kernel removed the O(T^2) masked-block DMA: p95 TTFT 649 ms
# vs 830 at 2048 (fewer tunnel RTs), p50 equal within noise, best prefill
# throughput (12.6k tok/s). Before the kernel fix, bigger steps LOST (see
# PARITY.md "TTFT lever").
PREFILL_BUDGET = int(os.environ.get("KGCT_BENCH_PREFILL_BUDGET", 4096))
WARMUP_WINDOWS = 3
BENCH_WINDOWS = int(os.environ.get("KGCT_BENCH_WINDOWS", 12))
MAX_NEW_TOKENS = PROMPT_LEN + DECODE_WINDOW * (WARMUP_WINDOWS + BENCH_WINDOWS + 4)


def _add_batch(engine, rng, vocab, tag):
    params = SamplingParams(temperature=0.0, max_tokens=MAX_NEW_TOKENS)
    t = time.perf_counter()
    for i in range(BATCH):
        prompt = rng.integers(1, vocab, PROMPT_LEN).tolist()
        engine.add_request(f"{tag}-{i}", prompt, params)
    return t


def _measure_host_rt_s() -> float:
    """Median host<->device round trip for a tiny dispatched op — on the
    tunnel-attached bench chip this is ~110 ms and dominates TTFT; reported
    separately so prefill compute is attributable."""
    x = jax.numpy.zeros((1,), jax.numpy.float32)
    f = jax.jit(lambda a: a + 1)
    f(x).block_until_ready()  # compile outside the timing
    ts = []
    for _ in range(5):
        t = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t)
    return sorted(ts)[len(ts) // 2]


def main() -> None:
    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    model_name = os.environ.get(
        "KGCT_BENCH_MODEL", "tinyllama-1.1b" if on_tpu else "debug-tiny")
    quant = os.environ.get("KGCT_BENCH_QUANT") or None
    page = PAGE if PAGE is not None else (128 if on_tpu else 16)
    pages_per_seq = (PROMPT_LEN + MAX_NEW_TOKENS) // page + 3
    cfg = EngineConfig(
        model=get_model_config(model_name).replace(quantization=quant),
        cache=CacheConfig(page_size=page, num_pages=BATCH * pages_per_seq + 1),
        scheduler=SchedulerConfig(
            max_num_seqs=BATCH, max_prefill_tokens=PREFILL_BUDGET,
            decode_buckets=(BATCH,), prefill_buckets=(PREFILL_BUDGET,),
            decode_window=DECODE_WINDOW))
    engine = LLMEngine(cfg, eos_token_id=None)
    rng = np.random.default_rng(0)
    vocab = cfg.model.vocab_size

    # --- warmup: compile prefill + decode-window programs -------------------
    _add_batch(engine, rng, vocab, "warm")
    while engine.scheduler.waiting:
        engine.step()
    for _ in range(WARMUP_WINDOWS):
        engine.step()
    for i in range(BATCH):
        engine.abort_request(f"warm-{i}")
    while engine.has_unfinished_requests():
        engine.step()

    # --- measured fresh batch: prefill throughput + TTFT --------------------
    host_rt_s = _measure_host_rt_s()
    t_submit = _add_batch(engine, rng, vocab, "bench")
    first_token_at: dict[str, float] = {}
    prefill_steps = 0
    t0 = time.perf_counter()
    while engine.scheduler.waiting:
        outs = engine.step()
        prefill_steps += 1
        now = time.perf_counter()
        for o in outs:
            if o.new_token_ids and o.request_id not in first_token_at:
                first_token_at[o.request_id] = now
    prefill_s = time.perf_counter() - t0
    prefill_toks_per_s = BATCH * PROMPT_LEN / prefill_s

    # --- steady-state decode throughput ------------------------------------
    # One priming step so the speculative window chain is in flight, then
    # BENCH_WINDOWS windows measured as 3 consecutive phases whose MEDIAN
    # rate is reported: the tunnel-attached chip shows transient dips
    # (±15% across minutes), and a median over temporally-close phases
    # keeps one bad window from defining the recorded number.
    outs = engine.step()
    phase_rates = []
    per_phase = max(1, BENCH_WINDOWS // 3)
    for _ in range(3):
        new_tokens = 0
        t0 = time.perf_counter()
        for _ in range(per_phase):
            outs = engine.step()
            if not outs:
                break
            new_tokens += sum(len(o.new_token_ids or []) for o in outs)
        elapsed = time.perf_counter() - t0
        if new_tokens:
            phase_rates.append(new_tokens / elapsed)
        if not outs:
            break
    toks_per_s = sorted(phase_rates)[len(phase_rates) // 2]

    ttft = sorted(t - t_submit for t in first_token_at.values())
    ttft_p50 = ttft[len(ttft) // 2] if ttft else float("nan")
    ttft_p95 = ttft[int(len(ttft) * 0.95)] if ttft else float("nan")

    # No silent wrong-class comparison: a model without a defined bar gets
    # vs_baseline null rather than a ~1B-class default.
    bar = A100_VLLM_TOKS_PER_S.get(model_name)
    result = {
        "metric": f"decode_tokens_per_sec_per_chip[{model_name},B={BATCH},ctx={PROMPT_LEN}]",
        "value": round(toks_per_s, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(toks_per_s / bar, 3) if bar else None,
        "backend": backend,
        "quantization": quant,
        "prefill_tokens_per_sec": round(prefill_toks_per_s, 1),
        "ttft_p50_ms": round(ttft_p50 * 1e3, 1),
        "ttft_p95_ms": round(ttft_p95 * 1e3, 1),
        # TTFT attribution: each engine prefill step pays one host<->device
        # round trip (the bench chip is tunnel-attached, ~110 ms) on top of
        # prefill compute; p50 TTFT ~= (steps_to_reach_p50_request) *
        # (per-step compute + RT).
        "ttft_breakdown": {
            "host_rt_ms": round(host_rt_s * 1e3, 1),
            "prefill_steps": prefill_steps,
            "prefill_wall_ms": round(prefill_s * 1e3, 1),
            "est_prefill_compute_ms": round(
                max(prefill_s - prefill_steps * host_rt_s, 0.0) * 1e3, 1),
        },
        # vs_baseline is normalized against a SELF-CHOSEN constant (the
        # reference publishes no numbers): representative single-A100 vLLM
        # decode throughput for this model class.
        "baseline_bar": {"value": bar,
                         "source": ("chosen constant (A100 vLLM class bar)"
                                    if bar else "no bar defined for model")},
        "decode_window": DECODE_WINDOW,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
